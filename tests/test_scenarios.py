"""Adversarial scenario matrix: campaign cells + no-divergence.

One small, short cell per fault family runs in tier-1 (N≤9 —
``sim/scenarios.py agent_scenario_cell`` with every gate asserted);
the full N=32 matrix is ``@slow`` and feeds ``SCENARIOS_N32.json``
via ``bench.py --scenarios``.  Unit-level coverage of the pieces the
cells compose — the one-way ``open_bi`` TOCTOU recheck, the HLC
max-delta rule under injected skew, equivocation observability through
the admin surface, and the no-divergence checker actually catching a
seeded divergence — lives here too.
"""

import asyncio

import pytest

from corrosion_tpu.faults import (
    EquivocatingPeer,
    FaultController,
    FaultPlan,
)


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


# ---------------------------------------------------------------------------
# tier-1 matrix cells (one per family, small N, every gate asserted)
# ---------------------------------------------------------------------------


def _cell(run, tmp_path, family, **kw):
    from corrosion_tpu.sim.scenarios import agent_scenario_cell

    kwargs = dict(
        n=5, seed=3, writes=4, heal_after=0.5, stall_ms=150.0,
        timeout=45.0, base_dir=str(tmp_path),
    )
    kwargs.update(kw)
    result = run(agent_scenario_cell(family, **kwargs))
    assert result["passed"], result["gates"]
    assert result["no_divergence"]["ok"], result["no_divergence"]
    assert result["live_p99_s"] is not None and result["live_p99_s"] >= 0
    return result


def test_scenario_cell_clock_skew(run, tmp_path):
    r = _cell(run, tmp_path, "clock_skew")
    # the skew family must actually skew: at least one node's derived
    # offset is nonzero, and no recorded lag ever went negative
    assert any(v != 0 for v in r["detail"]["clock_skew_ns"].values())


def test_scenario_cell_asym_partition(run, tmp_path):
    r = _cell(run, tmp_path, "asym_partition")
    assert r["injected"]["partition"] > 0


def test_scenario_cell_slow_io(run, tmp_path):
    r = _cell(run, tmp_path, "slow_io")
    assert r["injected"]["disk"] > 0
    assert r["injected"]["stall"] == 1


def test_scenario_cell_equivocation(run, tmp_path):
    r = _cell(run, tmp_path, "equivocation")
    eq = r["detail"]["equivocations"]
    assert eq.get("content", 0) >= 1
    assert eq.get("span", 0) >= 1
    assert eq.get("quarantined", 0) >= 1  # post-quarantine drops


def test_scenario_cell_compound(run, tmp_path):
    r = _cell(run, tmp_path, "compound")
    assert r["injected"]["partition"] > 0


# ---------------------------------------------------------------------------
# one-way open_bi TOCTOU: a partition arming mid-connect must not hand
# back a live session in the (freshly) blocked direction
# ---------------------------------------------------------------------------


def test_openbi_oneway_toctou(run, tmp_path):
    async def main():
        from corrosion_tpu.devcluster import Topology, run_inprocess
        from corrosion_tpu.agent.testing import wait_for

        plan = FaultPlan(
            seed=1, partition_blocks=2, oneway_blocks=((0, 1),),
        )
        ctrl = FaultController(plan)
        topo = Topology.parse("n0 -> n1")
        agents = await run_inprocess(
            topo, base_dir=str(tmp_path), faults=ctrl,
            subs_enabled=False, api_port=None,
        )
        try:
            await wait_for(
                lambda: all(
                    len(a.members.alive()) == 1 for a in agents.values()
                ),
                timeout=20,
            )
            n0, n1 = agents["n0"], agents["n1"]
            # wrap n0's hook: the FIRST "bi" consult passes (pre-split
            # state), then the split arms while the connect is in
            # flight — the TOCTOU window.  The post-connect
            # partition_check recheck must refuse the session.
            inner = n0.transport.fault_filter
            armed = {"done": False}

            def racing_hook(channel, addr):
                act = inner(channel, addr)
                if channel == "bi" and not armed["done"]:
                    armed["done"] = True
                    ctrl.split()
                return act

            n0.transport.fault_filter = racing_hook
            with pytest.raises(OSError):
                await n0.transport.open_bi(tuple(n1.gossip_addr))
            assert armed["done"]
            # the REVERSE direction stays open: n1 → n0 is not in the
            # one-way block matrix, sessions flow while the partition
            # is active
            chan = await n1.transport.open_bi(tuple(n0.gossip_addr))
            assert chan is not None
        finally:
            for a in agents.values():
                try:
                    await a.stop()
                except Exception:
                    pass

    run(main())


# ---------------------------------------------------------------------------
# HLC max-delta regression under injected skew
# ---------------------------------------------------------------------------


def test_hlc_rejects_updates_beyond_max_delta():
    """The 300 ms gossip clock-delta rule (types/hlc.py): a remote
    timestamp generated by a clock skewed past max_delta_ns is
    rejected — the local clock never ingests it — while a skew inside
    the bound merges normally."""
    import time

    from corrosion_tpu.types.hlc import (
        MAX_CLOCK_DELTA_NS,
        ClockDriftError,
        HLClock,
        skewed_now_ns,
    )

    local = HLClock()
    ahead = HLClock(now_ns=skewed_now_ns(MAX_CLOCK_DELTA_NS + 200_000_000))
    ts = ahead.new_timestamp()
    before = int(local.last)
    with pytest.raises(ClockDriftError):
        local.update_with_timestamp(ts)
    assert int(local.last) == before  # rejected, not ingested

    slightly_ahead = HLClock(now_ns=skewed_now_ns(50_000_000))
    ts2 = slightly_ahead.new_timestamp()
    local.update_with_timestamp(ts2)  # inside the bound: merges
    assert int(local.last) == int(ts2)

    # drift accumulates: a 1%-fast clock pulls ahead of its base
    base = time.time_ns()
    fast = skewed_now_ns(0, 0.01, base=time.time_ns)
    time.sleep(0.05)
    assert fast() > time.time_ns()


def test_agent_survives_skewed_changeset_and_clamps_lag(tmp_path):
    """A changeset stamped by a skewed-AHEAD origin clock: the data
    still applies (convergence must not hinge on a peer's oscillator),
    the local HLC rejects the merge, and the provenance lag clamps to
    0 instead of going negative (the PR 6 negative-lag clamp)."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=7)
        cv = peer.honest(1, "from-the-future")
        # re-stamp the changeset 2 s in the future (~a badly skewed
        # origin), far past the 300 ms delta rule
        import dataclasses
        import time

        from corrosion_tpu.types.hlc import Timestamp

        future_ts = Timestamp.pack(time.time_ns() + 2_000_000_000, 0)
        cv = dataclasses.replace(
            cv, changeset=dataclasses.replace(cv.changeset, ts=future_ts)
        )
        before = int(a.clock.last)
        assert a.handle_change(cv, ChangeSource.SYNC, rebroadcast=False)
        assert int(a.clock.last) == before  # merge rejected
        # the row applied anyway
        _, rows = a.storage.read_query(
            "SELECT text FROM tests WHERE id=1"
        )
        assert rows == [("from-the-future",)]
        # provenance lag clamped at 0, never negative
        rings = a.metrics.histogram_samples("corro_change_lag_seconds")
        samples = [s for ring in rings.values() for s in ring]
        assert samples and all(s == 0.0 for s in samples)
    finally:
        a.storage.close()


# ---------------------------------------------------------------------------
# equivocation observability: counter + quarantine reason in admin output
# ---------------------------------------------------------------------------


def test_equivocation_admin_observability(tmp_path):
    from corrosion_tpu.agent.admin import _handle
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=5)
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        members = _handle(a, {"cmd": "cluster_members"})["ok"]
        row = next(
            m for m in members if m["actor"] == peer.actor_id.hex()
        )
        assert row["quarantined"] is True
        assert row["quarantine_reason"] == "equivocation"
        # a transport-breaker "restore" must NOT clear the verdict
        a.members.quarantine_by_addr(("127.0.0.1", 9), False)
        assert a.members.get(peer.actor_id).quarantined
        # the rendered exposition carries the counter (the scrape
        # surface ClusterObserver.equivocations pools)
        from corrosion_tpu.agent.metrics import parse_prometheus_text

        parsed = parse_prometheus_text(
            a.metrics.render(a.metric_gauges())
        )
        fam = parsed["corro_sync_equivocations_total"]
        assert any(
            labels.get("kind") == "content" and v == 1
            for _n, labels, v in fam["samples"]
        )
    finally:
        a.storage.close()


def test_sync_reserve_content_drift_is_not_equivocation(tmp_path):
    """BROADCAST scope of content detection: a sync re-serve of an
    already-held version with DIFFERENT contents is legitimate — the
    serve path reconstructs versions from the current tables, so later
    overwrites shrink/change a re-collected changeset.  Comparing
    across paths would quarantine honest origins under ordinary
    overwrite workloads; the sync duplicate must absorb silently."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=13)
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        # same (actor, version), different content, SYNC source — a
        # compacted re-serve shape: absorbed, no detection
        assert not a.handle_change(cb, ChangeSource.SYNC,
                                   rebroadcast=False)
        assert a.metrics.get_counter_sum(
            "corro_sync_equivocations_total"
        ) == 0
        assert peer.actor_id not in a._equiv_quarantined
        # ...while the same conflicting content on the GOSSIP path is
        # hostile (gossiped bytes are immutable per version)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        # and a version first applied from SYNC records no digest, so
        # its later (legit, differing) broadcast never false-positives
        peer2 = EquivocatingPeer(seed=14)
        sa, sb = peer2.conflicting_pair(1)
        assert a.handle_change(sa, ChangeSource.SYNC, rebroadcast=False)
        assert not a.handle_change(sb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer2.actor_id not in a._equiv_quarantined
    finally:
        a.storage.close()


def test_equivocation_quarantine_expires_and_rearms(tmp_path):
    """The verdict is a bounded window (attribution is unsigned, so a
    framed honest actor must not be severed forever): traffic drops
    while it holds, re-admits after `equiv_quarantine_s` (member
    restored), and a real equivocator's next conflicting re-send
    re-quarantines immediately (digests survive expiry)."""
    import time

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path), equiv_quarantine_s=0.2)
    try:
        peer = EquivocatingPeer(seed=17)
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id in a._equiv_quarantined
        # while the verdict holds: dropped
        v2 = peer.honest(2, "held")
        assert not a.handle_change(v2, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        time.sleep(0.25)
        # expired: re-admitted, member restored
        v3 = peer.honest(3, "paroled")
        assert a.handle_change(v3, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert peer.actor_id not in a._equiv_quarantined
        assert not a.members.get(peer.actor_id).quarantined
        assert a.metrics.get_counter(
            "corro_members_quarantine_transitions_total",
            state="equivocation_expired",
        ) == 1
        # re-offense: the surviving digest re-quarantines at once
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id in a._equiv_quarantined
        assert a.members.get(peer.actor_id).quarantine_reason \
            == "equivocation"
    finally:
        a.storage.close()


def test_same_batch_conflicting_pair_detected(tmp_path):
    """A back-to-back conflicting pair landing in ONE merged apply
    batch is compared directly (no remembered digest exists yet) —
    the in-batch gate in _apply_complete_group."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=15)
        ca, cb = peer.conflicting_pair(1)
        src = ChangeSource.BROADCAST
        flags = a._apply_complete_group(
            peer.actor_id, [ca, cb], [src, src]
        )
        assert flags == [True, False]
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        assert peer.actor_id in a._equiv_quarantined
        # a byte-identical in-batch replay is NOT equivocation
        b_peer = EquivocatingPeer(seed=16)
        bait = b_peer.honest(1, "same")
        flags = a._apply_complete_group(
            b_peer.actor_id, [bait, bait], [src, src]
        )
        assert flags == [True, False]
        assert b_peer.actor_id not in a._equiv_quarantined
    finally:
        a.storage.close()


def test_equiv_digests_survive_restart(tmp_path):
    """An equivocator must not be able to wait out a REBOOT of its
    victim: accepted-content digests persist (__corro_equiv_digests)
    and reload on boot, so a conflicting re-send arriving after the
    detector restarted still compares against the accepted content and
    re-quarantines — while a byte-identical replay stays an absorbed
    duplicate."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    peer = EquivocatingPeer(seed=23)
    ca, cb = peer.conflicting_pair(1)
    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert (peer.actor_id, 1) in a._equiv_digests
    finally:
        a.storage.close()

    # restart from the same directory: in-memory state (dedup cache,
    # digests, quarantine) is gone — only what was persisted survives
    b = make_offline_agent(tmpdir=str(tmp_path))
    try:
        assert b._equiv_digests[(peer.actor_id, 1)] \
            == a._equiv_digests[(peer.actor_id, 1)]
        # byte-identical replay: absorbed, not equivocation
        assert not b.handle_change(ca, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id not in b._equiv_quarantined
        # the conflicting re-send the reboot was supposed to launder:
        # caught against the reloaded digest, actor re-quarantined
        assert not b.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert b.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        assert peer.actor_id in b._equiv_quarantined
    finally:
        b.storage.close()


def test_equiv_digest_table_bounded(tmp_path):
    """The durable digest table evicts in step with the in-memory FIFO
    — a hostile flood cannot grow it past the cache bound."""
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path), seen_cache_size=8)
    try:
        for v in range(1, 14):
            with a.storage._lock:
                a._remember_digest(b"\x07" * 16, v, bytes(16))
        assert len(a._equiv_digests) == 8
        (n,) = a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_equiv_digests"
        ).fetchone()
        assert n == 8
        assert min(v for _a, v in a._equiv_digests) == 6
    finally:
        a.storage.close()


def test_breaker_quarantine_reason_still_breaker(tmp_path):
    """The transport-evidence path keeps its reason (and its restore
    semantics): breaker open → reason 'breaker', half-open success →
    restored."""
    from corrosion_tpu.agent.members import Members

    ms = Members(b"self" * 4)
    actor = b"\x01" * 16
    ms.upsert(actor, ("127.0.0.1", 7))
    ms.quarantine_by_addr(("127.0.0.1", 7), True)
    m = ms.get(actor)
    assert m.quarantined and m.quarantine_reason == "breaker"
    ms.quarantine_by_addr(("127.0.0.1", 7), False)
    assert not ms.get(actor).quarantined
    assert ms.get(actor).quarantine_reason == ""


# ---------------------------------------------------------------------------
# the no-divergence checker must actually catch divergence
# ---------------------------------------------------------------------------


def test_no_divergence_checker_catches_seeded_divergence(tmp_path):
    """Feed two agents conflicting contents for one (actor, version),
    each node seeing only ITS content — the single-node detector is
    structurally blind here (nothing to compare against locally).  The
    cluster-level checker must flag both the table-state and
    conflicting-contents invariants — proving the campaign gate can
    actually fail, and that cross-node pooling covers the per-node
    detector's blind spot."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.devcluster import ClusterObserver
    from corrosion_tpu.types import ChangeSource

    for sub in ("a", "b", "c", "d"):
        (tmp_path / sub).mkdir()
    a = make_offline_agent(tmpdir=str(tmp_path / "a"))
    b = make_offline_agent(tmpdir=str(tmp_path / "b"))
    try:
        peer = EquivocatingPeer(seed=11)
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert b.handle_change(cb, ChangeSource.BROADCAST,
                               rebroadcast=False)
        obs = ClusterObserver({"a": a, "b": b})
        nodiv = obs.no_divergence()
        assert not nodiv["ok"]
        kinds = {v["kind"] for v in nodiv["violations"]}
        assert "table_state" in kinds
        assert "conflicting_contents" in kinds

        # and a genuinely identical pair is clean
        c = make_offline_agent(tmpdir=str(tmp_path / "c"))
        d = make_offline_agent(tmpdir=str(tmp_path / "d"))
        try:
            honest = EquivocatingPeer(seed=12).honest(1, "same")
            assert c.handle_change(honest, ChangeSource.BROADCAST,
                                   rebroadcast=False)
            assert d.handle_change(honest, ChangeSource.BROADCAST,
                                   rebroadcast=False)
            clean = ClusterObserver({"c": c, "d": d}).no_divergence()
            assert clean["ok"], clean
        finally:
            c.storage.close()
            d.storage.close()
    finally:
        a.storage.close()
        b.storage.close()


# ---------------------------------------------------------------------------
# the full matrix (bench.py --scenarios writes SCENARIOS_N32.json)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_scenario_matrix_n32(run, tmp_path):
    async def main():
        from corrosion_tpu.sim.scenarios import run_scenarios

        out = tmp_path / "SCENARIOS_N32.json"
        result = await run_scenarios(
            n=32, out_path=str(out), base_dir=str(tmp_path / "cluster")
        )
        assert result["all_cells_converged"], result
        assert result["no_divergence_all_cells"], result
        assert result["all_gates_passed"], result
        assert out.exists()

    run(main())


# ---------------------------------------------------------------------------
# signed changeset attribution (docs/faults.md): unframeable verdicts
# ---------------------------------------------------------------------------


def test_signed_equivocation_is_permanent_and_survives_restart(tmp_path):
    """A VERIFIED signed conflicting pair is a proof: the quarantine
    ignores the bounded window (deadline = inf), persists to
    __corro_equiv_proofs, and re-arms on reboot."""
    import math

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.crypto import seed_keypair

    sec, pub = seed_keypair(b"keyed-hostile")
    peer = EquivocatingPeer(seed=3, sig_secret=sec)
    directory = {peer.actor_id: pub}
    a = make_offline_agent(
        tmpdir=str(tmp_path), sig_pubkeys=directory,
        equiv_quarantine_s=5.0,
    )
    try:
        a.members.upsert(peer.actor_id, ("x", 1))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(
            ca, ChangeSource.BROADCAST, rebroadcast=False,
            meta=(None, 0, peer.sign_changeset(ca), None),
        )
        assert not a.handle_change(
            cb, ChangeSource.BROADCAST, rebroadcast=False,
            meta=(None, 0, peer.sign_changeset(cb), None),
        )
        assert a._equiv_quarantined[peer.actor_id] == math.inf
        m = a.members.get(peer.actor_id)
        assert m.quarantined
        assert m.quarantine_reason == "signed_equivocation"
        rows = a.storage.conn.execute(
            "SELECT actor_id, kind FROM __corro_equiv_proofs"
        ).fetchall()
        assert len(rows) == 1 and bytes(rows[0][0]) == peer.actor_id
        # both verifications ran and passed (the proof pair)
        assert a.metrics.get_counter(
            "corro_sig_verifications_total", result="ok") >= 1
    finally:
        a.storage.close()

    # reboot: the proof reloads and the verdict still drops traffic
    b = make_offline_agent(tmpdir=str(tmp_path), sig_pubkeys=directory)
    try:
        assert b._equiv_quarantined.get(peer.actor_id) == math.inf
        assert not b.handle_change(
            peer.honest(2, "post-reboot"), ChangeSource.BROADCAST,
            rebroadcast=False,
        )
    finally:
        b.storage.close()


def test_sig_failure_blames_relay_never_origin(tmp_path):
    """The unframeable property: tampered contents under the origin's
    passed-through signature convict the DELIVERING transport; the
    named origin keeps a clean record, and its untampered traffic
    keeps flowing."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.crypto import seed_keypair

    sec, pub = seed_keypair(b"honest-origin")
    origin = EquivocatingPeer(seed=5, sig_secret=sec)
    relay_actor = b"\x99" * 16
    a = make_offline_agent(
        tmpdir=str(tmp_path), sig_pubkeys={origin.actor_id: pub},
    )
    try:
        a.members.upsert(origin.actor_id, ("honest", 1))
        a.members.upsert(relay_actor, ("relayhost", 7))
        hv = origin.honest(1, "honest")
        sig = origin.sign_changeset(hv)
        assert a.handle_change(hv, ChangeSource.BROADCAST,
                               rebroadcast=False,
                               meta=(None, 0, sig, None))
        tampered = origin.tampered_copy(hv, "tampered")
        assert not a.handle_change(
            tampered, ChangeSource.BROADCAST, rebroadcast=False,
            meta=(None, 0, sig, ("relayhost", 7)),
        )
        # origin: no verdict, member record clean
        assert origin.actor_id not in a._equiv_quarantined
        assert not a.members.get(origin.actor_id).quarantined
        # relay: transport-class quarantine + tripped breaker
        mr = a.members.get(relay_actor)
        assert mr.quarantined and mr.quarantine_reason == "sig_failure"
        b = a.transport.breakers.get(("relayhost", 7)) \
            if a.transport else None
        assert b is None or b.is_open  # offline agent has no transport
        assert a.metrics.get_counter(
            "corro_sig_verifications_total", result="fail") >= 1
        # the origin's NEXT honest signed version still applies
        nxt = origin.honest(2, "still-flowing")
        assert a.handle_change(
            nxt, ChangeSource.BROADCAST, rebroadcast=False,
            meta=(None, 0, origin.sign_changeset(nxt), None),
        )
    finally:
        a.storage.close()


def test_evidence_verify_budget_bounds_flood(tmp_path):
    """A tampered-copy flood (one byte flipped per replay, so every
    copy is a fresh digest conflict) cannot buy a ~ms verify per
    message: past the token bucket the conflicting duplicate drops
    with NO verdict (result=skipped) — the origin stays clean and
    nothing applies, but the apply workers stop paying for Ed25519."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.crypto import seed_keypair

    sec, pub = seed_keypair(b"flooded-origin")
    origin = EquivocatingPeer(seed=11, sig_secret=sec)
    a = make_offline_agent(
        tmpdir=str(tmp_path), sig_pubkeys={origin.actor_id: pub},
        sig_evidence_verify_rate=4.0,  # burst 8
    )
    try:
        a.members.upsert(origin.actor_id, ("honest", 1))
        hv = origin.honest(1, "honest")
        sig = origin.sign_changeset(hv)
        assert a.handle_change(hv, ChangeSource.BROADCAST,
                               rebroadcast=False,
                               meta=(None, 0, sig, None))
        for i in range(50):
            assert not a.handle_change(
                origin.tampered_copy(hv, f"tamper-{i}"),
                ChangeSource.BROADCAST, rebroadcast=False,
                meta=(None, 0, sig, ("flood-host", 1000 + i)),
            )
        ran = a.metrics.get_counter(
            "corro_sig_verifications_total", result="fail")
        skipped = a.metrics.get_counter(
            "corro_sig_verifications_total", result="skipped")
        # burst 8 plus whatever refilled during the loop's few ms
        assert 0 < ran <= 12
        assert skipped >= 50 - 12
        # no verdict of ANY kind landed on the origin
        assert origin.actor_id not in a._equiv_quarantined
        assert not a.members.get(origin.actor_id).quarantined
        # and none of the tampered contents reached the tables
        _cols, rows = a.storage.read_query(
            "SELECT text FROM tests WHERE id = 1")
        assert rows == [("honest",)]
    finally:
        a.storage.close()

    # rate=0 opts out: every conflict verifies (pre-budget behavior)
    (tmp_path / "unbounded").mkdir()
    b = make_offline_agent(
        tmpdir=str(tmp_path / "unbounded"),
        sig_pubkeys={origin.actor_id: pub},
        sig_evidence_verify_rate=0.0,
    )
    try:
        b.members.upsert(origin.actor_id, ("honest", 1))
        assert b.handle_change(hv, ChangeSource.BROADCAST,
                               rebroadcast=False,
                               meta=(None, 0, sig, None))
        for i in range(10):
            assert not b.handle_change(
                origin.tampered_copy(hv, f"t{i}"),
                ChangeSource.BROADCAST, rebroadcast=False,
                meta=(None, 0, sig, ("flood-host", 2000 + i)),
            )
        assert b.metrics.get_counter(
            "corro_sig_verifications_total", result="fail") == 10
        assert b.metrics.get_counter(
            "corro_sig_verifications_total", result="skipped") == 0
    finally:
        b.storage.close()


def test_trip_breaker_bounded_under_rotating_addrs(tmp_path):
    """Verified-hostile evidence keyed by attacker-controlled
    ephemeral source addresses must not grow the breaker registry
    without bound: past the cap the oldest-opened entries are evicted
    (transport.prune_breakers), and a real Transport's insert path
    shares the same sweep."""
    from types import SimpleNamespace

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.agent.transport import (
        CircuitBreaker, prune_breakers,
    )

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        a.transport = SimpleNamespace(breakers={}, max_cached=4)
        for port in range(200):
            a._trip_breaker(("hostile", port))
        cap = 4 * 4
        assert len(a.transport.breakers) <= cap + 1
        # the survivors are the most recently tripped (a live offender
        # re-trips on its next evidence, so old ports are safe to drop)
        assert ("hostile", 199) in a.transport.breakers
        assert ("hostile", 0) not in a.transport.breakers
    finally:
        a.storage.close()

    # unit shape: healthy entries evict first, open ones only past cap
    breakers = {}
    for i in range(10):
        breakers[("h", i)] = CircuitBreaker(1, 1.0)
    breakers[("open", 0)] = CircuitBreaker(1, 1.0)
    breakers[("open", 0)].trip()
    prune_breakers(breakers, 4)
    assert ("open", 0) in breakers  # open survives while healthy go
    assert len(breakers) <= 4

    # closed-with-strikes entries (member churn accrues them forever)
    # must not dodge the bound: they evict after healthy, before open
    breakers = {}
    for i in range(10):
        b = CircuitBreaker(5, 1.0)
        b.record_failure()  # 0 < failures < threshold, not open
        breakers[("striked", i)] = b
    breakers[("open", 0)] = CircuitBreaker(1, 1.0)
    breakers[("open", 0)].trip()
    prune_breakers(breakers, 4)
    assert ("open", 0) in breakers
    assert len(breakers) <= 4

    # evicting an OPEN breaker fires on_evict so the owner can lift
    # the member quarantine it carried (a fresh breaker for the same
    # address closes silently — no transition event would ever fire)
    breakers = {}
    for i in range(10):
        b = CircuitBreaker(1, 1.0)
        b.trip()
        breakers[("o", i)] = b
    lifted = []
    prune_breakers(breakers, 4, on_evict=lifted.append)
    assert len(breakers) <= 4
    assert len(lifted) == 10 - len(breakers)
    assert all(a not in breakers for a in lifted)


def test_sig_failure_label_survives_breaker_transition(tmp_path):
    """The evidence-class label must be what sticks: _blame_relay
    trips the breaker FIRST (whose _on_breaker labels the member
    reason="breaker") and applies reason="sig_failure" after, so the
    equal-rank last-writer-wins relabel leaves the SPECIFIC evidence
    class visible in cluster_members."""
    from types import SimpleNamespace

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.crypto import seed_keypair

    sec, pub = seed_keypair(b"labeled-origin")
    origin = EquivocatingPeer(seed=21, sig_secret=sec)
    relay_actor = b"\x77" * 16
    a = make_offline_agent(
        tmpdir=str(tmp_path), sig_pubkeys={origin.actor_id: pub},
    )
    try:
        # a real breaker registry so _trip_breaker's _on_breaker
        # member-labeling path actually fires
        a.transport = SimpleNamespace(breakers={}, max_cached=16)
        a.members.upsert(origin.actor_id, ("honest", 1))
        a.members.upsert(relay_actor, ("relayhost", 7))
        hv = origin.honest(1, "honest")
        sig = origin.sign_changeset(hv)
        assert a.handle_change(hv, ChangeSource.BROADCAST,
                               rebroadcast=False,
                               meta=(None, 0, sig, None))
        assert not a.handle_change(
            origin.tampered_copy(hv, "tampered"),
            ChangeSource.BROADCAST, rebroadcast=False,
            meta=(None, 0, sig, ("relayhost", 7)),
        )
        mr = a.members.get(relay_actor)
        assert mr.quarantined
        assert mr.quarantine_reason == "sig_failure"
        assert a.transport.breakers[("relayhost", 7)].is_open
    finally:
        a.storage.close()


def test_spot_check_slot_not_consumed_by_unkeyed_actor(tmp_path):
    """In a partially-keyed cluster the interval slot belongs to
    actors that can actually be verified: an unkeyed actor's traffic
    must never claim it (verification would return None), or a chatty
    unkeyed actor starves the keyed actors' tripwire."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types.crypto import seed_keypair

    _sec, pub = seed_keypair(b"keyed-one")
    keyed = b"\x01" * 16
    unkeyed = b"\x02" * 16
    a = make_offline_agent(
        tmpdir=str(tmp_path), sig_pubkeys={keyed: pub},
        sig_spot_check_rate=1.0, sig_spot_check_min_interval_s=3600.0,
    )
    try:
        # a flood from the unkeyed actor admits nothing and, crucially,
        # leaves the interval slot unclaimed
        assert not any(a._spot_check_due(unkeyed, v) for v in range(50))
        assert a._spot_check_due(keyed, 1)   # slot still available
        assert not a._spot_check_due(keyed, 2)  # now interval-bound
    finally:
        a.storage.close()


def test_signed_proof_escalates_inf_unsigned_verdict(tmp_path):
    """equiv_quarantine_s=0 gives UNSIGNED verdicts an inf deadline
    too — a later signed proof must still relabel the standing verdict
    to signed_equivocation (the escalation is tracked by proof state,
    not inferred from the deadline), and the _pre_change drop path's
    Members re-assert must key on proof state the same way."""
    import math

    from corrosion_tpu.agent.testing import make_offline_agent

    actor = b"\x31" * 16
    a = make_offline_agent(tmpdir=str(tmp_path), equiv_quarantine_s=0.0)
    try:
        a.members.upsert(actor, ("x", 1))
        # unsigned verdict: inf deadline (hold=0) but unsigned reason
        a._note_equivocation(actor, "content")
        assert a._equiv_quarantined[actor] == math.inf
        assert a.members.get(actor).quarantine_reason == "equivocation"
        # an unsigned inf verdict must NOT masquerade as signed on the
        # drop path's re-assert (keyed on _equiv_proofed, not the
        # deadline)
        assert actor not in a._equiv_proofed
        # the signed proof (in-batch conflicting pairs reach the
        # verdict seam before the drop path arms) escalates in place
        a._note_equivocation(
            actor, "content",
            proof=(1, "content", b"msg-a", b"s" * 64, b"msg-b",
                   b"t" * 64),
        )
        assert a.members.get(actor).quarantine_reason \
            == "signed_equivocation"
        assert actor in a._equiv_proofed
        assert a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_equiv_proofs"
        ).fetchone()[0] == 1
        # a REPEAT proof does not re-fire the escalation transition
        before = a.metrics.get_counter(
            "corro_members_quarantine_transitions_total",
            state="signed_equivocation")
        a._note_equivocation(
            actor, "content",
            proof=(1, "content", b"msg-a", b"s" * 64, b"msg-b",
                   b"t" * 64),
        )
        assert a.metrics.get_counter(
            "corro_members_quarantine_transitions_total",
            state="signed_equivocation") == before
    finally:
        a.storage.close()


def test_sync_deadline_strikes_breaker(tmp_path):
    """A blown session deadline records one ordinary breaker failure
    (ambiguous evidence: threshold strikes before quarantine), so a
    slow-trickle server stops being re-selected round after round
    forever — the containment the vcluster campaign seam models."""
    from types import SimpleNamespace

    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path), breaker_threshold=3)
    try:
        a.transport = SimpleNamespace(breakers={}, max_cached=16)
        addr = ("trickler", 9)
        for _ in range(2):
            a._sync_client_reject("deadline", addr, strike=True)
        b = a.transport.breakers[addr]
        assert not b.is_open and b.failures == 2
        a._sync_client_reject("deadline", addr, strike=True)
        assert b.is_open  # threshold strikes opened it
        assert a.metrics.get_counter(
            "corro_sync_client_rejects_total", reason="deadline") == 3
    finally:
        a.storage.close()


def test_unsigned_conflict_keeps_bounded_window(tmp_path):
    """Without verifiable signatures the pre-signing behavior holds
    byte for byte: bounded-window quarantine, reason=equivocation."""
    import math

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource

    peer = EquivocatingPeer(seed=9)
    a = make_offline_agent(tmpdir=str(tmp_path), equiv_quarantine_s=60.0)
    try:
        a.members.upsert(peer.actor_id, ("x", 1))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        deadline = a._equiv_quarantined[peer.actor_id]
        assert deadline != math.inf
        assert a.members.get(peer.actor_id).quarantine_reason \
            == "equivocation"
        assert a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_equiv_proofs"
        ).fetchone()[0] == 0
    finally:
        a.storage.close()


def test_wire_byte_exact_with_signing_disabled(tmp_path):
    """The acceptance criterion's wire half: with no keys configured
    the emitted frames are byte-identical to the pre-signing envelope
    (traced v1 with propagation on, classic v0 with it off)."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types.actor import ClusterId
    from corrosion_tpu.types.payload import BroadcastV1, UniPayload

    peer = EquivocatingPeer(seed=1)
    cv = peer.honest(1, "x")
    (tmp_path / "on").mkdir()
    (tmp_path / "off").mkdir()
    a = make_offline_agent(tmpdir=str(tmp_path / "on"))
    b = make_offline_agent(
        tmpdir=str(tmp_path / "off"), bcast_trace_propagation=False,
    )
    try:
        classic = speedy.encode_uni_payload(UniPayload(
            broadcast=BroadcastV1(change=cv),
            cluster_id=ClusterId(0),
        ))
        assert a.encode_broadcast_frame(cv) == speedy.frame(
            speedy.encode_traced_uni(classic, None, 0)
        )
        assert b.encode_broadcast_frame(cv) == speedy.frame(classic)
    finally:
        a.storage.close()
        b.storage.close()


def test_signed_envelope_honors_trace_propagation_off(tmp_path):
    """The v2 envelope carries a structural trace slot, but signing
    must not become a side channel that re-enables wire trace context
    the operator turned off: with ``bcast_trace_propagation=False`` a
    signed frame keeps the signature and drops the traceparent."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer

    TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    cv = EquivocatingPeer(seed=1).honest(1, "x")
    sig = bytes(range(64))  # relayed pass-through; content is opaque here
    (tmp_path / "on").mkdir()
    (tmp_path / "off").mkdir()
    a = make_offline_agent(tmpdir=str(tmp_path / "on"))
    b = make_offline_agent(
        tmpdir=str(tmp_path / "off"), bcast_trace_propagation=False,
    )
    try:
        _, tp, hop, gsig = a.decode_uni_frame_meta(
            a.encode_broadcast_frame(cv, traceparent=TP, hop=1, sig=sig)[4:]
        )
        assert (tp, hop, gsig) == (TP, 1, sig)
        _, tp, hop, gsig = b.decode_uni_frame_meta(
            b.encode_broadcast_frame(cv, traceparent=TP, hop=1, sig=sig)[4:]
        )
        assert (tp, hop, gsig) == (None, 1, sig)
    finally:
        a.storage.close()
        b.storage.close()


def test_boot_reassert_skips_unsigned_inf_verdicts(run, tmp_path):
    """run()'s boot re-assert of permanent verdicts is keyed on the
    explicit proof set, not ``deadline == inf``: with
    ``equiv_quarantine_s=0`` an UNSIGNED verdict parks at inf too, and
    a pre-start verdict on a possibly-framed actor must never be
    boot-relabeled as a proven signed equivocator."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource

    async def main():
        import math

        peer = EquivocatingPeer(seed=9)
        a = Agent(AgentConfig(
            db_path=str(tmp_path / "corrosion.db"),
            schema_sql=TEST_SCHEMA, api_port=None,
            equiv_quarantine_s=0.0,
        ))
        try:
            # a real loopback addr: start() boots the SWIM loops and a
            # non-IP member host breaks announce-target parsing
            a.members.upsert(peer.actor_id, ("127.0.0.1", 1))
            ca, cb = peer.conflicting_pair(1)
            assert a.handle_change(ca, ChangeSource.BROADCAST,
                                   rebroadcast=False)
            assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                       rebroadcast=False)
            # unsigned verdict, parked at inf by the zero window
            assert a._equiv_quarantined[peer.actor_id] == math.inf
            assert peer.actor_id not in a._equiv_proofed
            assert a.members.get(peer.actor_id).quarantine_reason \
                == "equivocation"
            await a.start()
            assert a.members.get(peer.actor_id).quarantine_reason \
                == "equivocation"
        finally:
            await a.stop()

    run(main())


# ---------------------------------------------------------------------------
# Byzantine sync-serve client defenses (docs/faults.md)
# ---------------------------------------------------------------------------


def test_screen_sync_state_rejects_structural_liars(tmp_path):
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import ByzantineSyncServer
    from corrosion_tpu.types.actor import ActorId
    from corrosion_tpu.types.base import Version
    from corrosion_tpu.types.payload import SyncStateV1

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        for mode in ("lying_ranges", "absurd_needs"):
            byz = ByzantineSyncServer(seed=0, mode=mode)
            assert a._screen_sync_state(byz.advertised_state()) \
                == "advertised_range", mode
        # huge-but-sub-structural head passes the screen (the need cap
        # is its bound) and an honest state passes clean
        assert a._screen_sync_state(
            ByzantineSyncServer(seed=0, mode="huge_head")
            .advertised_state()
        ) is None
        honest = SyncStateV1(actor_id=ActorId(b"\x01" * 16))
        honest.heads[ActorId(b"\x02" * 16)] = Version(41)
        honest.need[ActorId(b"\x02" * 16)] = [(3, 9)]
        assert a._screen_sync_state(honest) is None
        # inverted partial seq spans are structural lies too
        hostile = SyncStateV1(actor_id=ActorId(b"\x01" * 16))
        hostile.heads[ActorId(b"\x02" * 16)] = Version(5)
        hostile.partial_need[ActorId(b"\x02" * 16)] = {
            Version(3): [(7, 2)]
        }
        assert a._screen_sync_state(hostile) == "advertised_range"
    finally:
        a.storage.close()


def test_allocate_needs_caps_hostile_head(tmp_path):
    """A head just under the structural-lie line must not allocate an
    unbounded need queue: the per-session cap bounds the round and
    counts the rejection."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import ByzantineSyncServer

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        byz = ByzantineSyncServer(seed=0, mode="huge_head")
        sessions = [{"member": None, "theirs": byz.advertised_state()}]
        a._allocate_needs(sessions, a.generate_sync())
        allocated = sum(
            len(v) for v in sessions[0]["needs"].values()
        )
        assert 0 < allocated <= a.SYNC_CLIENT_NEED_CAP
        assert a.metrics.get_counter(
            "corro_sync_client_rejects_total", reason="need_cap") >= 1
    finally:
        a.storage.close()


def test_byz_frame_garbage_and_oversize_are_contained(tmp_path):
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.faults import ByzantineSyncServer

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        garbage = ByzantineSyncServer(seed=0, mode="garbage_frames")
        payloads = speedy.FrameReader().feed(garbage.serve_frames({}))
        assert payloads  # frames deframe fine; the CONTENT is junk
        for p in payloads:
            import pytest as _pytest

            with _pytest.raises(speedy.SpeedyError):
                speedy.decode_sync_message(p)
        oversized = ByzantineSyncServer(seed=0, mode="oversized_frame")
        import pytest as _pytest

        with _pytest.raises(speedy.SpeedyError):
            speedy.FrameReader().feed(oversized.serve_frames({}))
        # slow-trickle never completes inside any sane deadline
        trickle = ByzantineSyncServer(seed=0, mode="slow_trickle")
        assert trickle.serve_duration() \
            > a.config.sync_session_deadline_s
    finally:
        a.storage.close()


def test_quarantine_reason_ranking():
    """Evidence ranking (docs/faults.md): transport-class reasons
    (breaker/sig_failure) clear each other on restore; an unsigned
    equivocation verdict outranks them; a signed proof outranks
    everything, survives address moves, and is never relabeled."""
    from corrosion_tpu.agent.members import Members

    ms = Members(b"\x01" * 16)
    actor = b"\x02" * 16
    ms.upsert(actor, ("h", 1))

    # transport class: sig_failure set, breaker restore clears it
    ms.set_quarantined(actor, True, reason="sig_failure")
    assert ms.get(actor).quarantine_reason == "sig_failure"
    ms.set_quarantined(actor, False, reason="breaker")
    assert not ms.get(actor).quarantined

    # unsigned verdict outranks breaker and survives its restore
    ms.set_quarantined(actor, True, reason="equivocation")
    ms.set_quarantined(actor, True, reason="breaker")
    assert ms.get(actor).quarantine_reason == "equivocation"
    ms.set_quarantined(actor, False, reason="breaker")
    assert ms.get(actor).quarantined

    # signed proof outranks the unsigned verdict and every later
    # weaker observation
    ms.set_quarantined(actor, True, reason="signed_equivocation")
    for weaker in ("breaker", "sig_failure", "equivocation"):
        ms.set_quarantined(actor, True, reason=weaker)
        assert ms.get(actor).quarantine_reason == "signed_equivocation"
        ms.set_quarantined(actor, False, reason=weaker)
        assert ms.get(actor).quarantined

    # an address move clears transport evidence but never a verdict
    ms.upsert(actor, ("h", 2), incarnation=1)
    m = ms.get(actor)
    assert m.quarantined
    assert m.quarantine_reason == "signed_equivocation"
    other = b"\x03" * 16
    ms.upsert(other, ("h", 3))
    ms.set_quarantined(other, True, reason="sig_failure")
    ms.upsert(other, ("h", 4), incarnation=1)
    assert not ms.get(other).quarantined
