"""Adversarial scenario matrix: campaign cells + no-divergence.

One small, short cell per fault family runs in tier-1 (N≤9 —
``sim/scenarios.py agent_scenario_cell`` with every gate asserted);
the full N=32 matrix is ``@slow`` and feeds ``SCENARIOS_N32.json``
via ``bench.py --scenarios``.  Unit-level coverage of the pieces the
cells compose — the one-way ``open_bi`` TOCTOU recheck, the HLC
max-delta rule under injected skew, equivocation observability through
the admin surface, and the no-divergence checker actually catching a
seeded divergence — lives here too.
"""

import asyncio

import pytest

from corrosion_tpu.faults import (
    EquivocatingPeer,
    FaultController,
    FaultPlan,
)


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


# ---------------------------------------------------------------------------
# tier-1 matrix cells (one per family, small N, every gate asserted)
# ---------------------------------------------------------------------------


def _cell(run, tmp_path, family, **kw):
    from corrosion_tpu.sim.scenarios import agent_scenario_cell

    kwargs = dict(
        n=5, seed=3, writes=4, heal_after=0.5, stall_ms=150.0,
        timeout=45.0, base_dir=str(tmp_path),
    )
    kwargs.update(kw)
    result = run(agent_scenario_cell(family, **kwargs))
    assert result["passed"], result["gates"]
    assert result["no_divergence"]["ok"], result["no_divergence"]
    assert result["live_p99_s"] is not None and result["live_p99_s"] >= 0
    return result


def test_scenario_cell_clock_skew(run, tmp_path):
    r = _cell(run, tmp_path, "clock_skew")
    # the skew family must actually skew: at least one node's derived
    # offset is nonzero, and no recorded lag ever went negative
    assert any(v != 0 for v in r["detail"]["clock_skew_ns"].values())


def test_scenario_cell_asym_partition(run, tmp_path):
    r = _cell(run, tmp_path, "asym_partition")
    assert r["injected"]["partition"] > 0


def test_scenario_cell_slow_io(run, tmp_path):
    r = _cell(run, tmp_path, "slow_io")
    assert r["injected"]["disk"] > 0
    assert r["injected"]["stall"] == 1


def test_scenario_cell_equivocation(run, tmp_path):
    r = _cell(run, tmp_path, "equivocation")
    eq = r["detail"]["equivocations"]
    assert eq.get("content", 0) >= 1
    assert eq.get("span", 0) >= 1
    assert eq.get("quarantined", 0) >= 1  # post-quarantine drops


def test_scenario_cell_compound(run, tmp_path):
    r = _cell(run, tmp_path, "compound")
    assert r["injected"]["partition"] > 0


# ---------------------------------------------------------------------------
# one-way open_bi TOCTOU: a partition arming mid-connect must not hand
# back a live session in the (freshly) blocked direction
# ---------------------------------------------------------------------------


def test_openbi_oneway_toctou(run, tmp_path):
    async def main():
        from corrosion_tpu.devcluster import Topology, run_inprocess
        from corrosion_tpu.agent.testing import wait_for

        plan = FaultPlan(
            seed=1, partition_blocks=2, oneway_blocks=((0, 1),),
        )
        ctrl = FaultController(plan)
        topo = Topology.parse("n0 -> n1")
        agents = await run_inprocess(
            topo, base_dir=str(tmp_path), faults=ctrl,
            subs_enabled=False, api_port=None,
        )
        try:
            await wait_for(
                lambda: all(
                    len(a.members.alive()) == 1 for a in agents.values()
                ),
                timeout=20,
            )
            n0, n1 = agents["n0"], agents["n1"]
            # wrap n0's hook: the FIRST "bi" consult passes (pre-split
            # state), then the split arms while the connect is in
            # flight — the TOCTOU window.  The post-connect
            # partition_check recheck must refuse the session.
            inner = n0.transport.fault_filter
            armed = {"done": False}

            def racing_hook(channel, addr):
                act = inner(channel, addr)
                if channel == "bi" and not armed["done"]:
                    armed["done"] = True
                    ctrl.split()
                return act

            n0.transport.fault_filter = racing_hook
            with pytest.raises(OSError):
                await n0.transport.open_bi(tuple(n1.gossip_addr))
            assert armed["done"]
            # the REVERSE direction stays open: n1 → n0 is not in the
            # one-way block matrix, sessions flow while the partition
            # is active
            chan = await n1.transport.open_bi(tuple(n0.gossip_addr))
            assert chan is not None
        finally:
            for a in agents.values():
                try:
                    await a.stop()
                except Exception:
                    pass

    run(main())


# ---------------------------------------------------------------------------
# HLC max-delta regression under injected skew
# ---------------------------------------------------------------------------


def test_hlc_rejects_updates_beyond_max_delta():
    """The 300 ms gossip clock-delta rule (types/hlc.py): a remote
    timestamp generated by a clock skewed past max_delta_ns is
    rejected — the local clock never ingests it — while a skew inside
    the bound merges normally."""
    import time

    from corrosion_tpu.types.hlc import (
        MAX_CLOCK_DELTA_NS,
        ClockDriftError,
        HLClock,
        skewed_now_ns,
    )

    local = HLClock()
    ahead = HLClock(now_ns=skewed_now_ns(MAX_CLOCK_DELTA_NS + 200_000_000))
    ts = ahead.new_timestamp()
    before = int(local.last)
    with pytest.raises(ClockDriftError):
        local.update_with_timestamp(ts)
    assert int(local.last) == before  # rejected, not ingested

    slightly_ahead = HLClock(now_ns=skewed_now_ns(50_000_000))
    ts2 = slightly_ahead.new_timestamp()
    local.update_with_timestamp(ts2)  # inside the bound: merges
    assert int(local.last) == int(ts2)

    # drift accumulates: a 1%-fast clock pulls ahead of its base
    base = time.time_ns()
    fast = skewed_now_ns(0, 0.01, base=time.time_ns)
    time.sleep(0.05)
    assert fast() > time.time_ns()


def test_agent_survives_skewed_changeset_and_clamps_lag(tmp_path):
    """A changeset stamped by a skewed-AHEAD origin clock: the data
    still applies (convergence must not hinge on a peer's oscillator),
    the local HLC rejects the merge, and the provenance lag clamps to
    0 instead of going negative (the PR 6 negative-lag clamp)."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=7)
        cv = peer.honest(1, "from-the-future")
        # re-stamp the changeset 2 s in the future (~a badly skewed
        # origin), far past the 300 ms delta rule
        import dataclasses
        import time

        from corrosion_tpu.types.hlc import Timestamp

        future_ts = Timestamp.pack(time.time_ns() + 2_000_000_000, 0)
        cv = dataclasses.replace(
            cv, changeset=dataclasses.replace(cv.changeset, ts=future_ts)
        )
        before = int(a.clock.last)
        assert a.handle_change(cv, ChangeSource.SYNC, rebroadcast=False)
        assert int(a.clock.last) == before  # merge rejected
        # the row applied anyway
        _, rows = a.storage.read_query(
            "SELECT text FROM tests WHERE id=1"
        )
        assert rows == [("from-the-future",)]
        # provenance lag clamped at 0, never negative
        rings = a.metrics.histogram_samples("corro_change_lag_seconds")
        samples = [s for ring in rings.values() for s in ring]
        assert samples and all(s == 0.0 for s in samples)
    finally:
        a.storage.close()


# ---------------------------------------------------------------------------
# equivocation observability: counter + quarantine reason in admin output
# ---------------------------------------------------------------------------


def test_equivocation_admin_observability(tmp_path):
    from corrosion_tpu.agent.admin import _handle
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=5)
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        members = _handle(a, {"cmd": "cluster_members"})["ok"]
        row = next(
            m for m in members if m["actor"] == peer.actor_id.hex()
        )
        assert row["quarantined"] is True
        assert row["quarantine_reason"] == "equivocation"
        # a transport-breaker "restore" must NOT clear the verdict
        a.members.quarantine_by_addr(("127.0.0.1", 9), False)
        assert a.members.get(peer.actor_id).quarantined
        # the rendered exposition carries the counter (the scrape
        # surface ClusterObserver.equivocations pools)
        from corrosion_tpu.agent.metrics import parse_prometheus_text

        parsed = parse_prometheus_text(
            a.metrics.render(a.metric_gauges())
        )
        fam = parsed["corro_sync_equivocations_total"]
        assert any(
            labels.get("kind") == "content" and v == 1
            for _n, labels, v in fam["samples"]
        )
    finally:
        a.storage.close()


def test_sync_reserve_content_drift_is_not_equivocation(tmp_path):
    """BROADCAST scope of content detection: a sync re-serve of an
    already-held version with DIFFERENT contents is legitimate — the
    serve path reconstructs versions from the current tables, so later
    overwrites shrink/change a re-collected changeset.  Comparing
    across paths would quarantine honest origins under ordinary
    overwrite workloads; the sync duplicate must absorb silently."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=13)
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        # same (actor, version), different content, SYNC source — a
        # compacted re-serve shape: absorbed, no detection
        assert not a.handle_change(cb, ChangeSource.SYNC,
                                   rebroadcast=False)
        assert a.metrics.get_counter_sum(
            "corro_sync_equivocations_total"
        ) == 0
        assert peer.actor_id not in a._equiv_quarantined
        # ...while the same conflicting content on the GOSSIP path is
        # hostile (gossiped bytes are immutable per version)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        # and a version first applied from SYNC records no digest, so
        # its later (legit, differing) broadcast never false-positives
        peer2 = EquivocatingPeer(seed=14)
        sa, sb = peer2.conflicting_pair(1)
        assert a.handle_change(sa, ChangeSource.SYNC, rebroadcast=False)
        assert not a.handle_change(sb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer2.actor_id not in a._equiv_quarantined
    finally:
        a.storage.close()


def test_equivocation_quarantine_expires_and_rearms(tmp_path):
    """The verdict is a bounded window (attribution is unsigned, so a
    framed honest actor must not be severed forever): traffic drops
    while it holds, re-admits after `equiv_quarantine_s` (member
    restored), and a real equivocator's next conflicting re-send
    re-quarantines immediately (digests survive expiry)."""
    import time

    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path), equiv_quarantine_s=0.2)
    try:
        peer = EquivocatingPeer(seed=17)
        a.members.upsert(peer.actor_id, ("127.0.0.1", 9))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id in a._equiv_quarantined
        # while the verdict holds: dropped
        v2 = peer.honest(2, "held")
        assert not a.handle_change(v2, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        time.sleep(0.25)
        # expired: re-admitted, member restored
        v3 = peer.honest(3, "paroled")
        assert a.handle_change(v3, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert peer.actor_id not in a._equiv_quarantined
        assert not a.members.get(peer.actor_id).quarantined
        assert a.metrics.get_counter(
            "corro_members_quarantine_transitions_total",
            state="equivocation_expired",
        ) == 1
        # re-offense: the surviving digest re-quarantines at once
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id in a._equiv_quarantined
        assert a.members.get(peer.actor_id).quarantine_reason \
            == "equivocation"
    finally:
        a.storage.close()


def test_same_batch_conflicting_pair_detected(tmp_path):
    """A back-to-back conflicting pair landing in ONE merged apply
    batch is compared directly (no remembered digest exists yet) —
    the in-batch gate in _apply_complete_group."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=15)
        ca, cb = peer.conflicting_pair(1)
        src = ChangeSource.BROADCAST
        flags = a._apply_complete_group(
            peer.actor_id, [ca, cb], [src, src]
        )
        assert flags == [True, False]
        assert a.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        assert peer.actor_id in a._equiv_quarantined
        # a byte-identical in-batch replay is NOT equivocation
        b_peer = EquivocatingPeer(seed=16)
        bait = b_peer.honest(1, "same")
        flags = a._apply_complete_group(
            b_peer.actor_id, [bait, bait], [src, src]
        )
        assert flags == [True, False]
        assert b_peer.actor_id not in a._equiv_quarantined
    finally:
        a.storage.close()


def test_equiv_digests_survive_restart(tmp_path):
    """An equivocator must not be able to wait out a REBOOT of its
    victim: accepted-content digests persist (__corro_equiv_digests)
    and reload on boot, so a conflicting re-send arriving after the
    detector restarted still compares against the accepted content and
    re-quarantines — while a byte-identical replay stays an absorbed
    duplicate."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ChangeSource

    peer = EquivocatingPeer(seed=23)
    ca, cb = peer.conflicting_pair(1)
    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert (peer.actor_id, 1) in a._equiv_digests
    finally:
        a.storage.close()

    # restart from the same directory: in-memory state (dedup cache,
    # digests, quarantine) is gone — only what was persisted survives
    b = make_offline_agent(tmpdir=str(tmp_path))
    try:
        assert b._equiv_digests[(peer.actor_id, 1)] \
            == a._equiv_digests[(peer.actor_id, 1)]
        # byte-identical replay: absorbed, not equivocation
        assert not b.handle_change(ca, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id not in b._equiv_quarantined
        # the conflicting re-send the reboot was supposed to launder:
        # caught against the reloaded digest, actor re-quarantined
        assert not b.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert b.metrics.get_counter(
            "corro_sync_equivocations_total", kind="content"
        ) == 1
        assert peer.actor_id in b._equiv_quarantined
    finally:
        b.storage.close()


def test_equiv_digest_table_bounded(tmp_path):
    """The durable digest table evicts in step with the in-memory FIFO
    — a hostile flood cannot grow it past the cache bound."""
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path), seen_cache_size=8)
    try:
        for v in range(1, 14):
            with a.storage._lock:
                a._remember_digest(b"\x07" * 16, v, bytes(16))
        assert len(a._equiv_digests) == 8
        (n,) = a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_equiv_digests"
        ).fetchone()
        assert n == 8
        assert min(v for _a, v in a._equiv_digests) == 6
    finally:
        a.storage.close()


def test_breaker_quarantine_reason_still_breaker(tmp_path):
    """The transport-evidence path keeps its reason (and its restore
    semantics): breaker open → reason 'breaker', half-open success →
    restored."""
    from corrosion_tpu.agent.members import Members

    ms = Members(b"self" * 4)
    actor = b"\x01" * 16
    ms.upsert(actor, ("127.0.0.1", 7))
    ms.quarantine_by_addr(("127.0.0.1", 7), True)
    m = ms.get(actor)
    assert m.quarantined and m.quarantine_reason == "breaker"
    ms.quarantine_by_addr(("127.0.0.1", 7), False)
    assert not ms.get(actor).quarantined
    assert ms.get(actor).quarantine_reason == ""


# ---------------------------------------------------------------------------
# the no-divergence checker must actually catch divergence
# ---------------------------------------------------------------------------


def test_no_divergence_checker_catches_seeded_divergence(tmp_path):
    """Feed two agents conflicting contents for one (actor, version),
    each node seeing only ITS content — the single-node detector is
    structurally blind here (nothing to compare against locally).  The
    cluster-level checker must flag both the table-state and
    conflicting-contents invariants — proving the campaign gate can
    actually fail, and that cross-node pooling covers the per-node
    detector's blind spot."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.devcluster import ClusterObserver
    from corrosion_tpu.types import ChangeSource

    for sub in ("a", "b", "c", "d"):
        (tmp_path / sub).mkdir()
    a = make_offline_agent(tmpdir=str(tmp_path / "a"))
    b = make_offline_agent(tmpdir=str(tmp_path / "b"))
    try:
        peer = EquivocatingPeer(seed=11)
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert b.handle_change(cb, ChangeSource.BROADCAST,
                               rebroadcast=False)
        obs = ClusterObserver({"a": a, "b": b})
        nodiv = obs.no_divergence()
        assert not nodiv["ok"]
        kinds = {v["kind"] for v in nodiv["violations"]}
        assert "table_state" in kinds
        assert "conflicting_contents" in kinds

        # and a genuinely identical pair is clean
        c = make_offline_agent(tmpdir=str(tmp_path / "c"))
        d = make_offline_agent(tmpdir=str(tmp_path / "d"))
        try:
            honest = EquivocatingPeer(seed=12).honest(1, "same")
            assert c.handle_change(honest, ChangeSource.BROADCAST,
                                   rebroadcast=False)
            assert d.handle_change(honest, ChangeSource.BROADCAST,
                                   rebroadcast=False)
            clean = ClusterObserver({"c": c, "d": d}).no_divergence()
            assert clean["ok"], clean
        finally:
            c.storage.close()
            d.storage.close()
    finally:
        a.storage.close()
        b.storage.close()


# ---------------------------------------------------------------------------
# the full matrix (bench.py --scenarios writes SCENARIOS_N32.json)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_scenario_matrix_n32(run, tmp_path):
    async def main():
        from corrosion_tpu.sim.scenarios import run_scenarios

        out = tmp_path / "SCENARIOS_N32.json"
        result = await run_scenarios(
            n=32, out_path=str(out), base_dir=str(tmp_path / "cluster")
        )
        assert result["all_cells_converged"], result
        assert result["no_divergence_all_cells"], result
        assert result["all_gates_passed"], result
        assert out.exists()

    run(main())
