"""Golden port of the reference's sync serve-side scenarios.

Mirrors ``crates/corro-agent/src/api/peer.rs`` ``test_handle_need``:
apply two versions from a foreign actor, then assert the exact wire
responses for a full need, a partial need of a fully-known version
(promoted to a full changeset), a partial need of an overwritten
version (read-time cleared detection: an EmptySet), and a full range
spanning live + overwritten versions (served newest first, the
overwritten version as an EmptySet).
"""

import asyncio
import os

import pytest

from corrosion_tpu.agent.runtime import ChangeSource
from corrosion_tpu.agent.testing import launch_test_agent
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import ActorId, SyncNeedV1, Version
from corrosion_tpu.types.change import Change, CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.changeset import Changeset, ChangeV1
from corrosion_tpu.agent.pack import pack_values


class _CaptureWriter:
    def __init__(self):
        self.buf = bytearray()

    def write(self, b: bytes) -> None:
        self.buf += b

    async def drain(self) -> None:
        pass


def _mk(pk, val, col_version, db_version, site):
    return Change(
        table="tests", pk=pack_values([pk]), cid="text", val=val,
        col_version=col_version, db_version=CrsqlDbVersion(db_version),
        seq=CrsqlSeq(0), site_id=site, cl=1,
    )


def test_serve_need_reference_scenarios():
    async def main():
        a = await launch_test_agent()
        try:
            foreign = os.urandom(16)
            ts = a.clock.new_timestamp()
            change1 = _mk(1, "one", 1, 1, foreign)
            change2 = _mk(2, "two", 1, 2, foreign)
            for v, ch in ((1, change1), (2, change2)):
                a.handle_change(
                    ChangeV1(
                        actor_id=ActorId(foreign),
                        changeset=Changeset.full(
                            Version(v), [ch], (0, 0), 0, ts
                        ),
                    ),
                    ChangeSource.SYNC,
                    rebroadcast=False,
                )
            bv = a.bookie.for_actor(foreign)
            assert bv.contains_version(1) and bv.contains_version(2)

            async def serve(need):
                w = _CaptureWriter()
                await a._serve_need(w, foreign, need)
                return [
                    speedy.decode_sync_message(p)
                    for p in speedy.FrameReader().feed(bytes(w.buf))
                ]

            # full need of v1: exactly change1 back, byte-faithful
            msgs = await serve(SyncNeedV1.full(1, 1))
            assert len(msgs) == 1
            cv = msgs[0]
            assert isinstance(cv, ChangeV1)
            assert cv.actor_id.bytes == foreign
            cs = cv.changeset
            assert cs.is_full and int(cs.version) == 1
            assert list(cs.changes) == [change1]
            assert tuple(map(int, cs.seqs)) == (0, 0) and int(cs.last_seq) == 0

            # partial need of a fully-known version promotes to full
            msgs = await serve(SyncNeedV1.partial(2, [(0, 0)]))
            assert len(msgs) == 1
            cs = msgs[0].changeset
            assert cs.is_full and int(cs.version) == 2
            assert list(cs.changes) == [change2]

            # v3 overwrites pk 1 -> v1's change rows vanish
            change3 = _mk(1, "one override", 2, 3, foreign)
            a.handle_change(
                ChangeV1(
                    actor_id=ActorId(foreign),
                    changeset=Changeset.full(
                        Version(3), [change3], (0, 0), 0,
                        a.clock.new_timestamp(),
                    ),
                ),
                ChangeSource.SYNC,
                rebroadcast=False,
            )

            # partial need of the overwritten version: read-time cleared
            # detection serves an EmptySet, not a hollow full changeset
            msgs = await serve(SyncNeedV1.partial(1, [(0, 0)]))
            assert len(msgs) == 1
            cs = msgs[0].changeset
            assert cs.is_empty_variant and not cs.changes
            assert tuple(map(int, cs.versions)) == (1, 1)

            # full range over live + overwritten versions: newest first,
            # the overwritten one last as an EmptySet (reference order)
            msgs = await serve(SyncNeedV1.full(1, 6))
            kinds = [
                (int(m.changeset.version)
                 if m.changeset.is_full else ("empty",) + tuple(
                     map(int, m.changeset.versions)))
                for m in msgs
            ]
            assert kinds == [3, 2, ("empty", 1, 1)]
            assert list(msgs[0].changeset.changes) == [change3]
            assert list(msgs[1].changeset.changes) == [change2]
        finally:
            await a.stop()

    asyncio.run(main())
