"""Serve-path benchmark harness checks.

Tier-1 runs the full ``bench.py --sync`` machinery at 500 versions (a
smoke: parity must hold, the live two-node backfill must converge);
the 10k-version headline gates (>=3x serve throughput, no event-loop
stall over 50 ms while serving) run in the @slow tier.
"""

import pytest

from bench import run_sync_bench


def test_sync_bench_smoke_500():
    out = run_sync_bench(n_versions=500, out_path=None, live=True)
    assert "error" not in out, out.get("error")
    # a served-bytes mismatch voids the headline — the smoke pins it
    assert out["parity_ok"] is True
    assert out["value"] is not None and out["value"] > 0
    pts = out["points"]
    assert pts["per_version"]["cold"]["served_bytes"] == \
        pts["batched"]["cold"]["served_bytes"] > 0
    assert out["live_backfill"]["converged"] is True


@pytest.mark.slow
def test_sync_bench_headline_10k():
    out = run_sync_bench(n_versions=10_000, out_path=None, live=True)
    assert "error" not in out, out.get("error")
    assert out["parity_ok"] is True
    # acceptance gates: >=3x cold serve throughput, and the batched
    # serve never stalls the event loop beyond 50 ms
    assert out["value"] >= 3.0, out
    assert out["points"]["batched"]["cold"]["max_stall_ms"] <= 50.0, out
    assert out["live_backfill"]["converged"] is True
