import random

import pytest

from corrosion_tpu.utils.ranges import RangeSet


def test_insert_coalesce_adjacent():
    rs = RangeSet()
    rs.insert(1, 5)
    rs.insert(6, 9)
    assert rs.spans() == [(1, 9)]


def test_insert_overlap_merge():
    rs = RangeSet([(1, 3), (10, 12)])
    rs.insert(2, 11)
    assert rs.spans() == [(1, 12)]


def test_insert_disjoint_kept_sorted():
    rs = RangeSet()
    rs.insert(10, 12)
    rs.insert(1, 2)
    rs.insert(5, 6)
    assert rs.spans() == [(1, 2), (5, 6), (10, 12)]


def test_remove_middle_splits():
    rs = RangeSet([(1, 10)])
    rs.remove(4, 6)
    assert rs.spans() == [(1, 3), (7, 10)]


def test_remove_edges():
    rs = RangeSet([(1, 10)])
    rs.remove(1, 3)
    assert rs.spans() == [(4, 10)]
    rs.remove(8, 12)
    assert rs.spans() == [(4, 7)]


def test_remove_across_spans():
    rs = RangeSet([(1, 3), (5, 7), (9, 11)])
    rs.remove(2, 10)
    assert rs.spans() == [(1, 1), (11, 11)]


def test_contains_and_contains_span():
    rs = RangeSet([(5, 10)])
    assert rs.contains(5) and rs.contains(10) and not rs.contains(11)
    assert rs.contains_span(6, 10)
    assert not rs.contains_span(6, 11)


def test_gaps():
    rs = RangeSet([(3, 4), (8, 9)])
    assert rs.gaps(1, 12) == [(1, 2), (5, 7), (10, 12)]
    assert rs.gaps(3, 9) == [(5, 7)]
    assert RangeSet().gaps(1, 5) == [(1, 5)]
    assert rs.gaps(3, 4) == []


def test_intersection_spans():
    rs = RangeSet([(1, 5), (10, 20)])
    assert rs.intersection_spans(3, 12) == [(3, 5), (10, 12)]


def test_count_min_max():
    rs = RangeSet([(1, 3), (10, 10)])
    assert rs.count() == 4
    assert rs.min() == 1 and rs.max() == 10


def test_randomized_against_set_model():
    rng = random.Random(42)
    rs = RangeSet()
    model = set()
    for _ in range(500):
        s = rng.randint(0, 120)
        e = s + rng.randint(0, 15)
        if rng.random() < 0.6:
            rs.insert(s, e)
            model.update(range(s, e + 1))
        else:
            rs.remove(s, e)
            model.difference_update(range(s, e + 1))
        # spans must be disjoint, sorted, non-adjacent, and match the model
        flat = set()
        prev_end = None
        for a, b in rs:
            assert a <= b
            if prev_end is not None:
                assert a > prev_end + 1
            prev_end = b
            flat.update(range(a, b + 1))
        assert flat == model
