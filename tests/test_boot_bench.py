"""Bootstrap-recovery benchmark harness checks.

Tier-1 runs the full ``bench.py --boot`` machinery at 500 versions (a
smoke: both arms converge, the snapshot arm genuinely installs, the
trajectory carries the install event); the 10k-version headline gates
(snapshot recovery >=5x faster than change-by-change, recovery within
the in-record budget) run in the @slow tier — matching the
WRITE/SYNC/APPLY bench pattern.
"""

import pytest

from bench import run_boot_bench


def test_boot_bench_smoke_500():
    out = run_boot_bench(n_versions=500, out_path=None)
    assert "error" not in out, out.get("error")
    gates = out["gates"]
    assert gates["both_converged"] is True
    assert gates["installed_via_snapshot"] is True
    assert gates["trajectory_has_install"] is True
    # at smoke scale the fixed session overheads dominate and host
    # load can swing either arm by more than the margin, so NO speedup
    # floor is asserted here — the 5x gate runs at 10k in @slow, and
    # the artifact lint re-asserts the committed record
    assert out["value"] is not None and out["value"] > 0, out
    sn = out["points"]["snapshot"]
    assert sn["snapshot_installs"] >= 1
    assert sn["snapshot_served_bytes"] > 0
    kinds = [e["kind"] for e in sn["trajectory"]]
    assert "snap_install" in kinds


@pytest.mark.slow
def test_boot_bench_headline_10k():
    out = run_boot_bench(n_versions=10_000, out_path=None)
    assert "error" not in out, out.get("error")
    assert all(out["gates"].values()), out["gates"]
    # the acceptance headline: snapshot bootstrap >=5x faster than
    # change-by-change at a 10k-version history, within budget
    assert out["value"] >= 5.0, out
    assert (out["points"]["snapshot"]["recovery_s"]
            <= out["recovery_budget_s"])
