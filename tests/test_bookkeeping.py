import sqlite3

import pytest

from corrosion_tpu.agent.bookkeeping import BookedVersions, Bookie, PartialVersion
from corrosion_tpu.types.hlc import Timestamp

A = b"\x01" * 16
B = b"\x02" * 16


def test_gap_creation_and_collapse():
    bv = BookedVersions(A)
    bv.apply_version(1, 10, 0)
    assert bv.last() == 1 and bv.needed_spans() == []
    # version 5 arrives: 2..4 become needed
    bv.apply_version(5, 11, 0)
    assert bv.needed_spans() == [(2, 4)]
    assert not bv.contains_version(3)
    bv.apply_version(3, 12, 0)
    assert bv.needed_spans() == [(2, 2), (4, 4)]
    bv.apply_version(2, 13, 0)
    bv.apply_version(4, 14, 0)
    assert bv.needed_spans() == []
    assert bv.contains_range(1, 5)


def test_cleared_ranges_absorb_needs_and_partials():
    bv = BookedVersions(A)
    bv.apply_version(10, 1, 0)  # gaps 1..9
    bv.insert_partial(7, (0, 3), 10)
    assert 7 in bv.partials
    bv.mark_cleared(1, 9, Timestamp(5))
    assert bv.needed_spans() == []
    assert bv.partials == {}
    assert bv.contains_range(1, 10)
    assert bv.last_cleared_ts == Timestamp(5)


def test_partial_assembly():
    bv = BookedVersions(A)
    p = bv.insert_partial(1, (0, 10), 30)
    assert not p.is_complete()
    assert p.gaps() == [(11, 30)]
    bv.insert_partial(1, (20, 30), 30)
    assert bv.partials[1].gaps() == [(11, 19)]
    p = bv.insert_partial(1, (11, 19), 30)
    assert p.is_complete()
    # promotion to applied
    bv.apply_version(1, 99, 30)
    assert 1 not in bv.partials
    assert bv.contains_version(1)


def test_partial_needs_feed():
    bv = BookedVersions(A)
    bv.insert_partial(2, (5, 9), 20)
    feeds = bv.partial_needs()
    assert feeds == {2: [(0, 4), (10, 20)]}


@pytest.fixture
def conn():
    c = sqlite3.connect(":memory:")
    c.isolation_level = None
    return c


def test_bookie_persistence_roundtrip(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    bv.apply_version(1, 100, 2)
    bookie.persist_version(A, 1, 100, 2, ts=111)
    bv.apply_version(5, 101, 0)
    bookie.persist_version(A, 5, 101, 0)
    bv.insert_partial(8, (0, 3), 50, Timestamp(7))
    bookie.persist_partial(A, 8, (0, 3), 50, ts=7)
    bv.mark_cleared(2, 3, Timestamp(9))
    bookie.persist_cleared(A, 2, 3, ts=9)

    # boot a fresh bookie from the same db: state must match
    reborn = Bookie(conn)
    bv2 = reborn.for_actor(A)
    assert bv2.last() == 8
    assert bv2.needed_spans() == [(4, 4), (6, 7)]
    assert bv2.contains_range(1, 3)
    assert 8 in bv2.partials and bv2.partials[8].gaps() == [(4, 50)]
    assert bv2.db_version_for(1) == 100


def test_bookie_cleared_range_merging(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(B)
    bv.mark_cleared(1, 5)
    bookie.persist_cleared(B, 1, 5)
    bv.mark_cleared(6, 10)
    bookie.persist_cleared(B, 6, 10)  # adjacent: must merge
    rows = conn.execute(
        "SELECT start_version, end_version FROM __corro_bookkeeping "
        "WHERE actor_id=? AND end_version IS NOT NULL",
        (B,),
    ).fetchall()
    assert rows == [(1, 10)]
    bv.mark_cleared(3, 7)
    bookie.persist_cleared(B, 3, 7)  # contained: still one row
    rows = conn.execute(
        "SELECT start_version, end_version FROM __corro_bookkeeping "
        "WHERE actor_id=? AND end_version IS NOT NULL",
        (B,),
    ).fetchall()
    assert rows == [(1, 10)]


def test_bookie_cleared_swallows_concrete_rows(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    bv.apply_version(1, 50, 0)
    bookie.persist_version(A, 1, 50, 0)
    bv.mark_cleared(1, 4)
    bookie.persist_cleared(A, 1, 4)
    rows = conn.execute(
        "SELECT start_version, end_version, db_version FROM __corro_bookkeeping "
        "WHERE actor_id=?",
        (A,),
    ).fetchall()
    assert rows == [(1, 4, None)]


def test_buffered_changes_roundtrip(conn):
    bookie = Bookie(conn)
    bookie.buffer_change(A, 3, 0, b"zero")
    bookie.buffer_change(A, 3, 2, b"two")
    bookie.buffer_change(A, 3, 1, b"one")
    assert bookie.buffered_changes(A, 3) == [(0, b"zero"), (1, b"one"), (2, b"two")]
    bookie.clear_partial(A, 3)
    assert bookie.buffered_changes(A, 3) == []
