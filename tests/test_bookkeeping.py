import sqlite3

import pytest

from corrosion_tpu.agent.bookkeeping import BookedVersions, Bookie, PartialVersion
from corrosion_tpu.types.hlc import Timestamp

A = b"\x01" * 16
B = b"\x02" * 16


def test_gap_creation_and_collapse():
    bv = BookedVersions(A)
    bv.apply_version(1, 10, 0)
    assert bv.last() == 1 and bv.needed_spans() == []
    # version 5 arrives: 2..4 become needed
    bv.apply_version(5, 11, 0)
    assert bv.needed_spans() == [(2, 4)]
    assert not bv.contains_version(3)
    bv.apply_version(3, 12, 0)
    assert bv.needed_spans() == [(2, 2), (4, 4)]
    bv.apply_version(2, 13, 0)
    bv.apply_version(4, 14, 0)
    assert bv.needed_spans() == []
    assert bv.contains_range(1, 5)


def test_cleared_ranges_absorb_needs_and_partials():
    bv = BookedVersions(A)
    bv.apply_version(10, 1, 0)  # gaps 1..9
    bv.insert_partial(7, (0, 3), 10)
    assert 7 in bv.partials
    bv.mark_cleared(1, 9)
    assert bv.needed_spans() == []
    assert bv.partials == {}
    assert bv.contains_range(1, 10)
    # clearing alone does NOT advance the watermark (only complete
    # information does — own compaction or a whole sync EmptySet group)
    assert bv.last_cleared_ts is None
    bv.update_cleared_ts(Timestamp(5))
    assert bv.last_cleared_ts == Timestamp(5)
    bv.update_cleared_ts(Timestamp(3))  # never moves backwards
    assert bv.last_cleared_ts == Timestamp(5)


def test_partial_assembly():
    bv = BookedVersions(A)
    p = bv.insert_partial(1, (0, 10), 30)
    assert not p.is_complete()
    assert p.gaps() == [(11, 30)]
    bv.insert_partial(1, (20, 30), 30)
    assert bv.partials[1].gaps() == [(11, 19)]
    p = bv.insert_partial(1, (11, 19), 30)
    assert p.is_complete()
    # promotion to applied
    bv.apply_version(1, 99, 30)
    assert 1 not in bv.partials
    assert bv.contains_version(1)


def test_partial_needs_feed():
    bv = BookedVersions(A)
    bv.insert_partial(2, (5, 9), 20)
    feeds = bv.partial_needs()
    assert feeds == {2: [(0, 4), (10, 20)]}


@pytest.fixture
def conn():
    c = sqlite3.connect(":memory:")
    c.isolation_level = None
    return c


def test_bookie_persistence_roundtrip(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    bv.apply_version(1, 100, 2)
    bookie.persist_version(A, 1, 100, 2, ts=111)
    bv.apply_version(5, 101, 0)
    bookie.persist_version(A, 5, 101, 0)
    bv.insert_partial(8, (0, 3), 50, Timestamp(7))
    bookie.persist_partial(A, 8, (0, 3), 50, ts=7)
    bv.mark_cleared(2, 3)
    bookie.persist_cleared(A, 2, 3, ts=9)

    # boot a fresh bookie from the same db: state must match
    reborn = Bookie(conn)
    bv2 = reborn.for_actor(A)
    assert bv2.last() == 8
    assert bv2.needed_spans() == [(4, 4), (6, 7)]
    assert bv2.contains_range(1, 3)
    assert 8 in bv2.partials and bv2.partials[8].gaps() == [(4, 50)]
    assert bv2.db_version_for(1) == 100


def test_bookie_cleared_range_merging(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(B)
    bv.mark_cleared(1, 5)
    bookie.persist_cleared(B, 1, 5)
    bv.mark_cleared(6, 10)
    bookie.persist_cleared(B, 6, 10)  # adjacent: must merge
    rows = conn.execute(
        "SELECT start_version, end_version FROM __corro_bookkeeping "
        "WHERE actor_id=? AND end_version IS NOT NULL",
        (B,),
    ).fetchall()
    assert rows == [(1, 10)]
    bv.mark_cleared(3, 7)
    bookie.persist_cleared(B, 3, 7)  # contained: still one row
    rows = conn.execute(
        "SELECT start_version, end_version FROM __corro_bookkeeping "
        "WHERE actor_id=? AND end_version IS NOT NULL",
        (B,),
    ).fetchall()
    assert rows == [(1, 10)]


def test_bookie_cleared_swallows_concrete_rows(conn):
    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    bv.apply_version(1, 50, 0)
    bookie.persist_version(A, 1, 50, 0)
    bv.mark_cleared(1, 4)
    bookie.persist_cleared(A, 1, 4)
    rows = conn.execute(
        "SELECT start_version, end_version, db_version FROM __corro_bookkeeping "
        "WHERE actor_id=?",
        (A,),
    ).fetchall()
    assert rows == [(1, 4, None)]


# ---------------------------------------------------------------------------
# Golden port of the reference's gap-collapse scenario test
# (``crates/corro-types/src/agent.rs:1814-2083`` — ``test_booked_insert_db``).
# Every insert/expect step below mirrors one step of the reference test, in
# the same order, including the persisted ``__corro_bookkeeping_gaps`` check
# and the reload-equality check at the end.
# ---------------------------------------------------------------------------


def _insert_everywhere(bookie, bv, all_versions, spans, dbv_counter):
    """Twin of the reference's ``insert_everywhere`` helper: applies the
    version ranges both in memory and through the persistence layer."""
    for start, end in spans:
        all_versions.insert(start, end)
        for v in range(start, end + 1):
            dbv_counter[0] += 1
            bv.apply_version(v, dbv_counter[0], 0)
            bookie.persist_version(bv.actor_id, v, dbv_counter[0], 0)


def _expect_gaps(bookie, bv, all_versions, expected):
    """Twin of the reference's ``expect_gaps`` helper: checks the persisted
    gap rows, in-memory needed set, containment, and max-version invariants."""
    rows = bookie.conn.execute(
        "SELECT start, end FROM __corro_bookkeeping_gaps WHERE actor_id=?"
        " ORDER BY start",
        (bv.actor_id,),
    ).fetchall()
    assert [tuple(r) for r in rows] == expected

    for start, end in all_versions.spans():
        assert bv.contains_range(start, end)

    for start, end in expected:
        for v in range(start, end + 1):
            assert not bv.contains_version(v), f"expected not to contain {v}"
            assert bv.needed.contains(v), f"expected needed to contain {v}"

    spans = all_versions.spans()
    assert bv.last() == (spans[-1][1] if spans else 0), (
        "expected last version not to increment"
    )


def test_booked_insert_db_full_then_subset(conn):
    """agent.rs test_booked_insert_db, first fresh state: a full range then
    an ineffective subset re-insert leave no gaps."""
    from corrosion_tpu.utils.ranges import RangeSet

    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    all_v, dbv = RangeSet(), [0]
    _insert_everywhere(bookie, bv, all_v, [(1, 20)], dbv)
    _expect_gaps(bookie, bv, all_v, [])
    _insert_everywhere(bookie, bv, all_v, [(1, 10)], dbv)
    _expect_gaps(bookie, bv, all_v, [])


def test_booked_insert_db_gap_create_fill(conn):
    """agent.rs test_booked_insert_db, second fresh state: create the 2..=3
    gap then fill it out of order."""
    from corrosion_tpu.utils.ranges import RangeSet

    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    all_v, dbv = RangeSet(), [0]
    _insert_everywhere(bookie, bv, all_v, [(1, 1), (4, 4)], dbv)
    _expect_gaps(bookie, bv, all_v, [(2, 3)])
    _insert_everywhere(bookie, bv, all_v, [(3, 3), (2, 2)], dbv)
    _expect_gaps(bookie, bv, all_v, [])


def test_booked_insert_db_reference_sequence(conn):
    """agent.rs test_booked_insert_db, third fresh state: the long scenario
    sequence — non-1 first version, partial overlaps from both ends,
    two-range bridging, ineffective re-inserts, full-range encompassing,
    multi-range partial touches — then reload equality."""
    from corrosion_tpu.utils.ranges import RangeSet

    bookie = Bookie(conn)
    bv = bookie.for_actor(A)
    all_v, dbv = RangeSet(), [0]

    # insert a non-1 first version
    _insert_everywhere(bookie, bv, all_v, [(5, 20)], dbv)
    _expect_gaps(bookie, bv, all_v, [(1, 4)])

    # a further change that does not overlap a gap
    _insert_everywhere(bookie, bv, all_v, [(6, 7)], dbv)
    _expect_gaps(bookie, bv, all_v, [(1, 4)])

    # a further change that does overlap a gap
    _insert_everywhere(bookie, bv, all_v, [(3, 7)], dbv)
    _expect_gaps(bookie, bv, all_v, [(1, 2)])

    _insert_everywhere(bookie, bv, all_v, [(1, 2)], dbv)
    _expect_gaps(bookie, bv, all_v, [])

    _insert_everywhere(bookie, bv, all_v, [(25, 25)], dbv)
    _expect_gaps(bookie, bv, all_v, [(21, 24)])

    _insert_everywhere(bookie, bv, all_v, [(30, 35)], dbv)
    _expect_gaps(bookie, bv, all_v, [(21, 24), (26, 29)])

    # overlapping partially from the end
    _insert_everywhere(bookie, bv, all_v, [(19, 22)], dbv)
    _expect_gaps(bookie, bv, all_v, [(23, 24), (26, 29)])

    # overlapping partially from the start
    _insert_everywhere(bookie, bv, all_v, [(24, 25)], dbv)
    _expect_gaps(bookie, bv, all_v, [(23, 23), (26, 29)])

    # overlapping 2 ranges
    _insert_everywhere(bookie, bv, all_v, [(23, 27)], dbv)
    _expect_gaps(bookie, bv, all_v, [(28, 29)])

    # ineffective insert of already known ranges
    _insert_everywhere(bookie, bv, all_v, [(1, 20)], dbv)
    _expect_gaps(bookie, bv, all_v, [(28, 29)])

    # overlapping no ranges, but encompassing a full range
    _insert_everywhere(bookie, bv, all_v, [(27, 30)], dbv)
    _expect_gaps(bookie, bv, all_v, [])

    # touching multiple ranges, partially: create gaps 36..=39 and 46..=49
    _insert_everywhere(bookie, bv, all_v, [(40, 45)], dbv)
    _insert_everywhere(bookie, bv, all_v, [(50, 55)], dbv)
    _insert_everywhere(bookie, bv, all_v, [(38, 47)], dbv)
    _expect_gaps(bookie, bv, all_v, [(36, 37), (48, 49)])

    # loading a fresh Bookie from the conn must reproduce identical state
    reborn = Bookie(conn)
    bv2 = reborn.for_actor(A)
    assert bv2.needed_spans() == bv.needed_spans()
    assert bv2.last() == bv.last()
    for start, end in all_v.spans():
        assert bv2.contains_range(start, end)
    for start, end in bv.needed_spans():
        for v in range(start, end + 1):
            assert not bv2.contains_version(v)


def test_buffered_changes_roundtrip(conn):
    bookie = Bookie(conn)
    bookie.buffer_change(A, 3, 0, b"zero")
    bookie.buffer_change(A, 3, 2, b"two")
    bookie.buffer_change(A, 3, 1, b"one")
    assert bookie.buffered_changes(A, 3) == [(0, b"zero"), (1, b"one"), (2, b"two")]
    bookie.clear_partial(A, 3)
    assert bookie.buffered_changes(A, 3) == []
