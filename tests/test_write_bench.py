"""Write-path benchmark harness checks.

Tier-1 runs the full ``bench.py --write`` machinery at 500 transactions
(a smoke: converged-state parity must hold across modes, versions stay
gapless); the 10k-transaction headline gates (>= 2.5x combined
throughput at 32 writers, combined event-loop max stall <= 50 ms
sampled at 5 ms) run in the @slow tier.
"""

import pytest

from bench import run_write_bench


def test_write_bench_smoke_500():
    out = run_write_bench(sizes=(500,), writers=(8,), out_path=None)
    assert "error" not in out, out.get("error")
    # a converged-state mismatch voids the headline — the smoke pins it
    assert out["value"] is not None and out["value"] > 0
    (p,) = out["points"]
    assert p["parity_ok"] is True
    assert p["combined"]["n_committed"] == p["per_tx"]["n_committed"] > 0
    # the combiner actually combined: mean group size above 1
    assert p["combined"]["mean_group_size"] > 1.0
    assert out["stall_gate"]["combined_max_stall_ms"] >= 0.0


@pytest.mark.slow
def test_write_bench_headline_10k():
    out = run_write_bench(sizes=(1000, 10_000), writers=(1, 8, 32),
                          out_path=None)
    assert "error" not in out, out.get("error")
    headline = next(
        p for p in out["points"]
        if p["n_tx"] == 10_000 and p["writers"] == 32
    )
    # acceptance gates: >= 2.5x combined 32-writer throughput, parity,
    # and the combined path's bounded stall-gate burst stays under
    # 50 ms (the sweep columns span 20-60 s windows, where a 2-core
    # host's scheduler alone exceeds the bar — see idle_max_stall_ms)
    assert out["value"] >= 2.5, out
    assert out["stall_gate"]["combined_max_stall_ms"] <= 50.0, out
    assert headline["parity_ok"] is True
