"""Broadcast/apply policy pins.

Parity targets: drop_oldest_broadcast drops the MOST-transmitted payloads
(broadcast/mod.rs:782-801), local broadcasts go to ALL ring0 members plus
a global sample (broadcast/mod.rs:586-702) with per-payload sent_to
exclusion, idle agents make no broadcast-loop wakeups, and change applies
run concurrently (≤5 batches in flight, handlers.rs:742-956).
"""

from __future__ import annotations

import asyncio
import random

import pytest

from corrosion_tpu.agent.members import Member, Members, MemberState
from corrosion_tpu.agent.runtime import _drop_most_transmitted
from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from corrosion_tpu.types import ActorId, ChangeSource, ChangeV1, Changeset
from corrosion_tpu.types.base import CrsqlSeq, Version


def test_drop_policy_drops_most_transmitted_first():
    # entries: (due, frame, cv, remaining, sent_to); smaller remaining =
    # more sends so far.  With cap 2, the two entries with the SMALLEST
    # remaining must go.
    pending = [
        (0.0, b"", "fresh", 5, set()),
        (0.0, b"", "stale", 1, set()),
        (0.0, b"", "mid", 3, set()),
        (0.0, b"", "stale2", 2, set()),
    ]
    dropped = _drop_most_transmitted(pending, 2)
    assert dropped == 2
    assert sorted(p[2] for p in pending) == ["fresh", "mid"]


def test_drop_policy_noop_under_cap():
    pending = [(0.0, b"", "a", 1, set())]
    assert _drop_most_transmitted(pending, 5) == 0
    assert len(pending) == 1


def _member(i: int, rtt: float) -> Member:
    m = Member(actor_id=bytes([i]) * 16, addr=("127.0.0.1", 10000 + i))
    m.note_rtt(rtt)
    return m


def test_local_fanout_sends_to_all_ring0():
    """On loopback every peer is ring0: a local change's first
    transmission reaches every one of them (the r2 cap of k//2 starved
    local fanout)."""
    members = Members(b"\x00" * 16)
    for i in range(1, 9):
        m = _member(i, rtt=1.0)  # all under the 6 ms ring0 bar
        members.upsert(m.actor_id, m.addr)
        members.record_rtt(m.actor_id, 1.0)
    picked = members.sample(3, random.Random(0), ring0_first=True)
    assert len(picked) == 8  # all ring0, uncapped


def test_fanout_mixes_ring0_and_global_sample():
    members = Members(b"\x00" * 16)
    for i in range(1, 4):
        members.upsert(bytes([i]) * 16, ("127.0.0.1", 10000 + i))
        members.record_rtt(bytes([i]) * 16, 1.0)  # ring0
    for i in range(4, 10):
        members.upsert(bytes([i]) * 16, ("127.0.0.1", 10000 + i))
        members.record_rtt(bytes([i]) * 16, 50.0)  # not ring0
    picked = members.sample(2, random.Random(0), ring0_first=True)
    ring0_picked = [m for m in picked if m.is_ring0]
    far_picked = [m for m in picked if not m.is_ring0]
    assert len(ring0_picked) == 3  # all of ring0
    assert len(far_picked) == 2  # plus k sampled from the rest


def test_sample_excludes_already_sent():
    members = Members(b"\x00" * 16)
    for i in range(1, 6):
        members.upsert(bytes([i]) * 16, ("127.0.0.1", 10000 + i))
    sent = {bytes([1]) * 16, bytes([2]) * 16}
    picked = members.sample(10, random.Random(0), ring0_first=False,
                            exclude=sent)
    assert {m.actor_id for m in picked}.isdisjoint(sent)
    assert len(picked) == 3


def test_idle_agent_makes_no_broadcast_wakeups():
    async def main():
        a = await launch_test_agent()
        await asyncio.sleep(0.3)  # settle any startup flushes
        before = a._bcast_wakeups
        await asyncio.sleep(1.0)
        assert a._bcast_wakeups - before <= 1, (
            "idle broadcast loop must block, not poll"
        )
        await a.stop()

    asyncio.run(main())


def test_apply_batches_overlap(tmp_path):
    """With the apply path briefly blocked and the queue loaded, the
    change loop keeps up to max_concurrent_applies batches in flight —
    observed as ≥2 concurrently-executing _apply_batch calls."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        fake_actor = b"\x42" * 16
        # many single-change changesets from a fake remote actor
        a.storage._lock.acquire()
        try:
            for v in range(1, 60):
                cs = Changeset.empty((Version(v), Version(v)),
                                     a.clock.new_timestamp())
                a.enqueue_change(
                    ChangeV1(actor_id=ActorId(fake_actor), changeset=cs),
                    ChangeSource.BROADCAST,
                )
                # let the change loop batch + dispatch while the storage
                # lock stays held, stacking workers
                await asyncio.sleep(0.005)
                if a._apply_max_overlap >= 2:
                    break
        finally:
            a.storage._lock.release()
        await wait_for(lambda: not a._ingest, timeout=10)
        assert a._apply_max_overlap >= 2
        assert (
            a._apply_max_overlap <= a.config.max_concurrent_applies
        )
        await a.stop()

    asyncio.run(main())


def test_cleared_since_filters_by_ts(tmp_path):
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        actor = a.actor_id
        with a.storage._lock:
            a.bookie.persist_cleared(actor, 1, 3, ts=100)
            a.bookie.persist_cleared(actor, 10, 12, ts=200)
        # grouped by stamping ts, oldest group first; strictly newer
        # than the requester's watermark
        assert a.bookie.cleared_since(actor) == [
            (100, [(1, 3)]),
            (200, [(10, 12)]),
        ]
        assert a.bookie.cleared_since(actor, 150) == [(200, [(10, 12)])]
        assert a.bookie.cleared_since(actor, 200) == []
        assert a.bookie.cleared_since(actor, 250) == []
        await a.stop()

    asyncio.run(main())


def test_rtt_topology_bins_members_and_trims():
    """The `rtt dump` capture path: members bin into 1-based RTT tiers
    by ring mean, unsampled members are reported separately (never
    binned), and trailing empty tiers are trimmed so the weights tuple
    is exactly what `measured_ring` consumes."""
    from corrosion_tpu.agent.members import rtt_tier_of, rtt_topology

    members = Members(b"\x00" * 16)
    # two ring0-fast (tier 1), one metro (tier 2: 6<=rtt<12), three
    # regional (tier 3: 12<=rtt<24); nothing beyond -> tiers 4-6 trim
    for i, rtt in enumerate((1.0, 2.0, 8.0, 15.0, 16.0, 20.0), start=1):
        members.upsert(bytes([i]) * 16, ("127.0.0.1", 10000 + i))
        members.record_rtt(bytes([i]) * 16, rtt)
    members.upsert(bytes([99]) * 16, ("127.0.0.1", 10099))  # no samples

    doc = rtt_topology(members)
    assert doc["topology"] == "measured_ring"
    assert doc["weights"] == [2, 1, 3]
    assert doc["rtt_tiers"] == 3
    assert doc["members_sampled"] == 6
    assert doc["members_unsampled"] == 1
    assert all(n["tier"] == rtt_tier_of(n["rtt_ms"]) for n in doc["nodes"])

    # custom edges re-bin: one coarse 10ms edge -> 2 tiers, all binned
    doc2 = rtt_topology(members, edges=(10.0,))
    assert doc2["weights"] == [3, 3]
    assert doc2["tier_edges_ms"] == [10.0]


def test_admin_rtt_dump_serves_topology(tmp_path):
    """The admin `rtt_dump` command round-trips the capture doc over
    the admin socket, honoring custom (validated) tier edges."""
    from corrosion_tpu.agent.admin import AdminClient

    import asyncio as aio

    async def main():
        sock = str(tmp_path / "admin.sock")
        a = await launch_test_agent(tmpdir=str(tmp_path), admin_path=sock)
        for i in range(1, 4):
            a.members.upsert(bytes([i]) * 16, ("127.0.0.1", 10000 + i))
            a.members.record_rtt(bytes([i]) * 16, float(i * 7))

        def call(cmd, **kw):
            c = AdminClient(sock)
            try:
                return c.call(cmd, **kw)
            finally:
                c.close()

        doc = await aio.to_thread(call, "rtt_dump")
        assert doc["topology"] == "measured_ring"
        assert sum(doc["weights"]) == 3
        with pytest.raises(RuntimeError, match="tier_edges_ms"):
            await aio.to_thread(call, "rtt_dump", tier_edges_ms=[5.0, 5.0])
        await a.stop()

    aio.run(main())
