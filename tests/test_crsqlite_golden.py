"""Golden parity tests: our CRDT engine vs the real cr-sqlite extension.

SURVEY §7.1 / VERDICT round-1 item 2(a): replay identical op sequences on
two replica clusters — one backed by :class:`corrosion_tpu.agent.storage.
CrConn` (our engine over stock sqlite3), one by the vendored cr-sqlite
native extension (:class:`corrosion_tpu.bridge.CrsqliteRef`) — exchanging
changes through each engine's own replication mechanism, and assert the
replicated *data tables* bit-match at every exchange point.

This pins our merge semantics (LWW biggest col_version, tie → biggest
value in cr-sqlite's type-enum order INTEGER > FLOAT > TEXT > BLOB >
NULL, numeric/bytewise within a type; causal-length delete/resurrect)
to the actual C implementation the reference ships.
"""

from __future__ import annotations

import random

import pytest

from corrosion_tpu.agent.storage import CrConn
from corrosion_tpu.bridge import CrsqliteRef, crsqlite_available
from corrosion_tpu.bridge.crsqlite_ref import _sort_key

pytestmark = pytest.mark.skipif(
    not crsqlite_available(),
    reason="vendored cr-sqlite extension not loadable",
)

# `v` has no type name → no affinity: values keep their storage class, so
# cross-type tie-breaks actually exercise cr-sqlite's type-enum ordering
# (INTEGER > FLOAT > TEXT > BLOB > NULL) instead of being coerced first.
# `bar` is PK-only: replication rides causal-length sentinel rows.
SCHEMA = (
    "CREATE TABLE foo ("
    " id INTEGER NOT NULL PRIMARY KEY,"
    " a TEXT, b INTEGER, c REAL, v);"
    "CREATE TABLE bar ("
    " x INTEGER NOT NULL, y INTEGER NOT NULL, PRIMARY KEY (x, y))"
)
TABLES = ("foo", "bar")

# Values spanning every SQLite storage class.
VALUE_POOL = [
    None, -7, 0, 1, 10, 2**40, 0.5, -2.25, 10.0,
    "", "a", "z", "hello", "héllo", "10",
    b"", b"\x00", b"blob", b"\xff\xff",
]


class DualCluster:
    """N logical replicas, each realized in both engines."""

    def __init__(self, n: int, tmp_path):
        self.refs = []
        self.mine = []
        for i in range(n):
            ref = CrsqliteRef(":memory:")
            ref.conn.executescript(SCHEMA)
            for t in TABLES:
                ref.as_crr(t)
            self.refs.append(ref)

            c = CrConn(str(tmp_path / f"mine_{i}.db"), site_id=ref.site_id)
            c.conn.executescript(SCHEMA)
            for t in TABLES:
                c.as_crr(t)
            self.mine.append(c)

    def close(self):
        for r in self.refs:
            r.close()
        for c in self.mine:
            c.close()

    # -- ops (applied to both engines) ---------------------------------

    def run(self, i: int, sql: str, params=()):
        self.refs[i].execute(sql, params)
        self.mine[i].execute(sql, params)

    def exchange(self, i: int, j: int):
        """One-way: replica i sends everything it knows to replica j."""
        self.refs[j].apply(self.refs[i].changes())
        self.mine[j].apply_changes(_my_all_changes(self.mine[i]))

    def assert_parity(self, label: str = ""):
        for idx, (r, m) in enumerate(zip(self.refs, self.mine)):
            for table in TABLES:
                ref_rows = r.data(table)
                my_cols, my_raw = m.read_query(f"SELECT * FROM {table}")
                my_rows = sorted(
                    (tuple(row) for row in my_raw), key=_sort_key
                )
                assert my_rows == ref_rows, (
                    f"{label}: replica {idx} table {table} diverged from "
                    f"cr-sqlite:\n"
                    f"  crsqlite: {ref_rows}\n  ours:     {my_rows}"
                )

    def live_pks(self, i: int):
        return {
            row[0]
            for row in self.refs[i].conn.execute("SELECT id FROM foo")
        }


def _my_all_changes(c: CrConn):
    out = []
    for _, sid in c.conn.execute(
        "SELECT ordinal, site_id FROM __corro_sites ORDER BY ordinal"
    ):
        sid = bytes(sid)
        out.extend(
            c.collect_changes(
                (0, 1 << 60), None if sid == c.site_id else sid
            )
        )
    return out


def test_insert_update_exchange_parity(tmp_path):
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo VALUES (1, 'x', 10, 0.5, NULL)")
    cl.run(0, "UPDATE foo SET a='y' WHERE id=1")
    cl.exchange(0, 1)
    cl.assert_parity("after exchange")
    cl.run(1, "UPDATE foo SET a='z', b=20 WHERE id=1")
    cl.exchange(1, 0)
    cl.assert_parity("after return exchange")
    cl.close()


@pytest.mark.parametrize(
    "va,vb",
    [
        (10, 20),
        (10, "10"),           # integer vs text: integer wins
        ("abc", b"abc"),      # text vs blob: text wins
        (None, 0),            # null loses to everything
        (1.5, 1),             # real vs integer: integer wins (enum order!)
        (1.5, 2.5),           # real vs real: numeric
        ("héllo", "hello"),   # utf-8 byte ordering
        (b"\x00", b""),
    ],
)
def test_concurrent_insert_tie_break(tmp_path, va, vb):
    """Both replicas insert the same pk concurrently with col_version 1 —
    the merged cell must be cr-sqlite's 'biggest value wins'.  Uses the
    no-affinity column `v` so values keep their storage class."""
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo (id, v) VALUES (5, ?)", (va,))
    cl.run(1, "INSERT INTO foo (id, v) VALUES (5, ?)", (vb,))
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity(f"tie {va!r} vs {vb!r}")
    # and both replicas agree with each other
    assert cl.refs[0].data("foo") == cl.refs[1].data("foo")
    cl.close()


def test_delete_vs_update_conflict(tmp_path):
    """Concurrent delete vs update: causal length decides (delete wins
    over the same generation's update)."""
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo VALUES (1, 'x', 1, NULL, NULL)")
    cl.exchange(0, 1)
    cl.assert_parity("seeded")
    cl.run(0, "DELETE FROM foo WHERE id=1")
    cl.run(1, "UPDATE foo SET a='updated' WHERE id=1")
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity("delete vs update")
    assert cl.refs[0].data("foo") == cl.refs[1].data("foo")
    cl.close()


def test_resurrect_parity(tmp_path):
    """Delete then re-insert (higher causal length) vs concurrent update
    of the dead generation: the resurrected generation must win."""
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo VALUES (2, 'gen1', 1, NULL, NULL)")
    cl.exchange(0, 1)
    cl.run(0, "DELETE FROM foo WHERE id=2")
    cl.run(0, "INSERT INTO foo (id, a) VALUES (2, 'gen2')")
    cl.run(1, "UPDATE foo SET b=99 WHERE id=2")
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity("resurrect")
    assert cl.refs[0].data("foo") == cl.refs[1].data("foo")
    cl.close()


def test_delete_then_exchange_both_ways(tmp_path):
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo VALUES (3, 'x', 1, NULL, NULL)")
    cl.exchange(0, 1)
    cl.run(1, "DELETE FROM foo WHERE id=3")
    cl.exchange(1, 0)
    cl.assert_parity("remote delete")
    assert cl.live_pks(0) == set()
    cl.close()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_ops_convergence_parity(tmp_path, seed):
    """The main golden property test: 3 replicas, randomized interleaved
    inserts/updates/deletes with randomized pairwise exchanges; parity is
    asserted after every exchange and total convergence at the end."""
    rng = random.Random(seed)
    n = 3
    cl = DualCluster(n, tmp_path)
    cols = ("a", "b", "c", "v")

    for step in range(120):
        i = rng.randrange(n)
        roll = rng.random()
        live = sorted(cl.live_pks(i))
        if roll < 0.12:
            j = rng.choice([x for x in range(n) if x != i])
            cl.exchange(i, j)
            cl.assert_parity(f"seed {seed} step {step} exchange {i}->{j}")
        elif roll < 0.5 or not live:
            pk = rng.randrange(1, 6)
            if pk in live:
                continue
            cl.run(
                i,
                "INSERT INTO foo (id, a, b, c, v) VALUES (?, ?, ?, ?, ?)",
                (pk, rng.choice(VALUE_POOL), rng.choice(VALUE_POOL),
                 rng.choice(VALUE_POOL), rng.choice(VALUE_POOL)),
            )
        elif roll < 0.85:
            pk = rng.choice(live)
            col = rng.choice(cols)
            cl.run(
                i,
                f"UPDATE foo SET {col}=? WHERE id=?",
                (rng.choice(VALUE_POOL), pk),
            )
        else:
            pk = rng.choice(live)
            cl.run(i, "DELETE FROM foo WHERE id=?", (pk,))

    # full anti-entropy: two all-to-all rounds guarantee convergence
    for _ in range(2):
        for i in range(n):
            for j in range(n):
                if i != j:
                    cl.exchange(i, j)
    cl.assert_parity(f"seed {seed} final")
    base = cl.refs[0].data("foo")
    for idx in range(1, n):
        assert cl.refs[idx].data("foo") == base, "cr-sqlite cluster diverged"
    cl.close()


def test_pkonly_insert_delete_parity(tmp_path):
    """PK-only tables replicate via '-1' sentinel rows (ADVICE round-1:
    our engine used to generate invalid SQL for these)."""
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO bar VALUES (1, 2)")
    cl.run(0, "INSERT INTO bar VALUES (3, 4)")
    cl.exchange(0, 1)
    cl.assert_parity("pk-only insert")
    assert cl.refs[1].data("bar") == [(1, 2), (3, 4)]
    cl.run(1, "DELETE FROM bar WHERE x=1")
    cl.exchange(1, 0)
    cl.assert_parity("pk-only delete")
    assert cl.refs[0].data("bar") == [(3, 4)]
    # concurrent delete vs re-insert (resurrect) on a pk-only row
    cl.run(0, "DELETE FROM bar WHERE x=3")
    cl.run(1, "DELETE FROM bar WHERE x=3")
    cl.run(1, "INSERT INTO bar VALUES (3, 4)")
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity("pk-only resurrect")
    assert cl.refs[0].data("bar") == cl.refs[1].data("bar")
    cl.close()


def test_as_crr_backfill_parity(tmp_path):
    """as_crr on a populated table must backfill clock entries so
    pre-existing rows replicate (ADVICE round-1: ours silently never
    replicated them)."""
    ref = CrsqliteRef(":memory:")
    ref.conn.executescript(SCHEMA)
    ref.execute("INSERT INTO foo (id, a, b) VALUES (1, 'old', 10)")
    ref.execute("INSERT INTO foo (id, a, b) VALUES (2, 'older', 20)")
    for t in TABLES:
        ref.as_crr(t)

    mine = CrConn(str(tmp_path / "m.db"), site_id=ref.site_id)
    mine.conn.executescript(SCHEMA)
    mine.conn.execute("INSERT INTO foo (id, a, b) VALUES (1, 'old', 10)")
    mine.conn.execute("INSERT INTO foo (id, a, b) VALUES (2, 'older', 20)")
    for t in TABLES:
        mine.as_crr(t)
    assert mine.peek_backfills(), "backfill should allocate a version"
    mine.clear_backfills()

    # fresh peers receive the backfilled rows through each engine's pipeline
    peer_ref = CrsqliteRef(":memory:")
    peer_ref.conn.executescript(SCHEMA)
    for t in TABLES:
        peer_ref.as_crr(t)
    peer_ref.apply(ref.changes())

    peer_mine = CrConn(str(tmp_path / "p.db"), site_id=peer_ref.site_id)
    peer_mine.conn.executescript(SCHEMA)
    for t in TABLES:
        peer_mine.as_crr(t)
    peer_mine.apply_changes(_my_all_changes(mine))

    _, raw = peer_mine.read_query("SELECT * FROM foo")
    got = sorted((tuple(r) for r in raw), key=_sort_key)
    assert got == peer_ref.data("foo") == [
        (1, "old", 10, None, None), (2, "older", 20, None, None)
    ]
    ref.close(); mine.close(); peer_ref.close(); peer_mine.close()


def test_pk_update_parity(tmp_path):
    """UPDATEs that change primary-key columns re-identify the row:
    delete sentinel for the old pk, insert sentinel for the new pk,
    cell clocks re-keyed in place (full-exchange converges; cr-sqlite's
    own delta-only transfer diverges identically by design)."""
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO foo (id, a, b) VALUES (1, 'x', 10)")
    cl.exchange(0, 1)
    cl.run(0, "UPDATE foo SET id=2 WHERE id=1")
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity("pk update")
    assert cl.refs[0].data("foo") == cl.refs[1].data("foo")
    # pk update combined with a data change in the same statement
    cl.run(1, "UPDATE foo SET id=3, a='moved' WHERE id=2")
    cl.exchange(1, 0)
    cl.exchange(0, 1)
    cl.assert_parity("pk+data update")
    cl.close()


def test_pkonly_pk_update_parity(tmp_path):
    cl = DualCluster(2, tmp_path)
    cl.run(0, "INSERT INTO bar VALUES (1, 2)")
    cl.exchange(0, 1)
    cl.run(0, "UPDATE bar SET y=3 WHERE x=1")
    cl.exchange(0, 1)
    cl.exchange(1, 0)
    cl.assert_parity("pk-only pk update")
    assert cl.refs[0].data("bar") == cl.refs[1].data("bar") == [(1, 3)]
    cl.close()


def test_change_stream_seq_alignment(tmp_path):
    """The emitted change stream's (cid, col_version, cl, seq) tuples must
    match cr-sqlite's exactly — fresh inserts number cells from seq 0,
    deletes/resurrects consume a sentinel slot first."""
    cl = DualCluster(1, tmp_path)
    cl.run(0, "INSERT INTO foo (id, a, b) VALUES (1, 'x', 10)")
    cl.run(0, "DELETE FROM foo WHERE id=1")
    cl.run(0, "INSERT INTO foo (id, a) VALUES (1, 'z')")
    ref_stream = [
        (r[2], r[4], r[7], r[8]) for r in cl.refs[0].changes()
    ]  # (cid, col_version, cl, seq) ordered by db_version, seq
    my_stream = [
        (c.cid, c.col_version, c.cl, int(c.seq))
        for c in _my_all_changes(cl.mine[0])
    ]
    assert my_stream == ref_stream, (my_stream, ref_stream)
    cl.close()
