"""CRDT merge kernel vs a plain-Python oracle of the cr-sqlite rule:
larger cl wins; tie -> larger col_version; tie -> larger value."""

import contextlib
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.ops import DEFAULT_CODEC, merge_cells, merge_keys, scatter_merge
from corrosion_tpu.ops.keys import KeyCodec, WIDE_CODEC


def codec_ctx(codec):
    """Wide (int64) codecs need x64 enabled."""
    if codec.total_bits > 31:
        return jax.enable_x64(True)
    return contextlib.nullcontext()


def oracle_merge(a, b):
    # a, b: (cl, ver, val) tuples
    return max(a, b)


def rand_cell(rng, codec):
    return (
        rng.randint(0, codec.max_cl),
        rng.randint(0, codec.max_ver),
        rng.randint(0, codec.max_val),
    )


@pytest.mark.parametrize("codec", [DEFAULT_CODEC, WIDE_CODEC], ids=["i32", "i64"])
def test_pack_unpack_roundtrip(codec):
    rng = random.Random(1)
    with codec_ctx(codec):
        cells = [rand_cell(rng, codec) for _ in range(256)]
        cl, ver, val = (jnp.array(x) for x in zip(*cells))
        keys = codec.pack(cl, ver, val)
        ucl, uver, uval = codec.unpack(keys)
        np.testing.assert_array_equal(ucl, cl)
        np.testing.assert_array_equal(uver, ver)
        np.testing.assert_array_equal(uval, val)


@pytest.mark.parametrize("codec", [DEFAULT_CODEC, WIDE_CODEC], ids=["i32", "i64"])
def test_packed_order_is_lexicographic(codec):
    rng = random.Random(2)
    with codec_ctx(codec):
        cells_a = [rand_cell(rng, codec) for _ in range(512)]
        cells_b = [rand_cell(rng, codec) for _ in range(512)]
        ka = codec.pack(*map(jnp.array, zip(*cells_a)))
        kb = codec.pack(*map(jnp.array, zip(*cells_b)))
        packed_lt = np.asarray(ka < kb).tolist()
        lex_lt = [a < b for a, b in zip(cells_a, cells_b)]
        assert packed_lt == lex_lt


def test_merge_matches_oracle_elementwise():
    rng = random.Random(3)
    codec = DEFAULT_CODEC
    a = [rand_cell(rng, codec) for _ in range(512)]
    b = [rand_cell(rng, codec) for _ in range(512)]
    ka = codec.pack(*map(jnp.array, zip(*a)))
    kb = codec.pack(*map(jnp.array, zip(*b)))
    merged = merge_keys(ka, kb)
    expect = [oracle_merge(x, y) for x, y in zip(a, b)]
    got = list(zip(*(np.asarray(x).tolist() for x in codec.unpack(merged))))
    assert [tuple(g) for g in got] == expect


def test_merge_is_join_semilattice():
    # commutative, associative, idempotent — batched over random triples
    rng = random.Random(4)
    codec = DEFAULT_CODEC
    mk = lambda cells: codec.pack(*map(jnp.array, zip(*cells)))
    a = mk([rand_cell(rng, codec) for _ in range(256)])
    b = mk([rand_cell(rng, codec) for _ in range(256)])
    c = mk([rand_cell(rng, codec) for _ in range(256)])
    np.testing.assert_array_equal(merge_keys(a, b), merge_keys(b, a))
    np.testing.assert_array_equal(
        merge_keys(a, merge_keys(b, c)), merge_keys(merge_keys(a, b), c)
    )
    np.testing.assert_array_equal(merge_keys(a, a), a)


def test_merge_cells_reduces_replicas():
    codec = DEFAULT_CODEC
    # 3 replicas x 4 cells
    cl = jnp.array([[1, 1, 2, 1], [1, 3, 1, 1], [1, 1, 1, 1]])
    ver = jnp.array([[5, 1, 1, 2], [1, 1, 1, 2], [9, 1, 1, 2]])
    val = jnp.array([[0, 7, 0, 3], [0, 0, 0, 9], [4, 0, 0, 9]])
    keys = codec.pack(cl, ver, val)
    merged = codec.unpack(merge_cells(keys))
    mcl, mver, mval = (np.asarray(x).tolist() for x in merged)
    # cell0: same cl -> ver 9 wins; cell1: cl 3 wins; cell2: cl 2 wins;
    # cell3: all tie on (1,2) -> biggest value 9
    assert mcl == [1, 3, 2, 1]
    assert mver == [9, 1, 1, 2]
    assert mval == [4, 0, 0, 9]


def test_scatter_merge_delivers_and_merges_duplicates():
    codec = DEFAULT_CODEC
    state = codec.pack(
        jnp.ones(4, jnp.int32), jnp.ones(4, jnp.int32), jnp.zeros(4, jnp.int32)
    )
    targets = jnp.array([2, 2, 0, 9])  # 9 out of range -> dropped
    msgs = codec.pack(
        jnp.array([1, 1, 1, 3]),
        jnp.array([4, 6, 1, 9]),
        jnp.array([0, 0, 0, 0]),
    )
    out = scatter_merge(state, targets, msgs)
    cl, ver, val = (np.asarray(x).tolist() for x in codec.unpack(out))
    assert ver == [1, 1, 6, 1]  # node2 got max(4,6); node0 msg didn't raise ver
    assert cl == [1, 1, 1, 1]  # out-of-range cl=3 message dropped


def test_is_live_parity():
    codec = DEFAULT_CODEC
    keys = codec.pack(
        jnp.array([1, 2, 3, 0]), jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32)
    )
    assert np.asarray(codec.is_live(keys)).tolist() == [True, False, True, False]


def test_wide_codec_guarded_without_x64():
    with pytest.raises(RuntimeError, match="x64"):
        WIDE_CODEC.pack(jnp.array([1]), jnp.array([2]), jnp.array([3]))


def test_codec_layout_validation():
    with pytest.raises(ValueError):
        KeyCodec(cl_bits=20, ver_bits=24, val_bits=24)


