"""Minimal synchronous pgwire v3 client used by the PG server tests
(no Postgres driver is available in the image)."""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple


class PgClient:
    def __init__(self, host: str, port: int, user: str = "test",
                 database: str = "db", timeout: float = 10.0,
                 tls: bool = False, ca_file: Optional[str] = None):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        if tls:
            # SSLRequest then upgrade, like libpq sslmode=require
            import ssl

            self.sock.sendall(struct.pack(">II", 8, 80877103))
            answer = self.sock.recv(1)
            if answer != b"S":
                raise ConnectionError(f"server refused TLS: {answer!r}")
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            if ca_file:
                ctx.load_verify_locations(ca_file)
                ctx.check_hostname = False
            else:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self.sock = ctx.wrap_socket(self.sock)
        params = b""
        for k, v in (("user", user), ("database", database)):
            params += k.encode() + b"\x00" + v.encode() + b"\x00"
        params += b"\x00"
        body = struct.pack(">I", 196608) + params
        self.sock.sendall(struct.pack(">I", len(body) + 4) + body)
        self._buf = b""
        # read until ReadyForQuery
        self.params: dict = {}
        self.backend_key: Optional[Tuple[int, int]] = None
        for tag, payload in self._messages_until(b"Z"):
            if tag == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            elif tag == b"K":
                self.backend_key = struct.unpack(">II", payload)
        self.txn_status = None
        self.last_error_codes: List[str] = []

    # -- plumbing --------------------------------------------------------

    def _recv_msg(self) -> Tuple[bytes, bytes]:
        while len(self._buf) < 5:
            self._buf += self._recv()
        tag = self._buf[:1]
        (ln,) = struct.unpack(">I", self._buf[1:5])
        while len(self._buf) < 1 + ln:
            self._buf += self._recv()
        payload = self._buf[5 : 1 + ln]
        self._buf = self._buf[1 + ln :]
        return tag, payload

    def _recv(self) -> bytes:
        data = self.sock.recv(65536)
        if not data:
            raise ConnectionError("server closed")
        return data

    def _messages_until(self, end_tag: bytes):
        while True:
            tag, payload = self._recv_msg()
            yield tag, payload
            if tag == end_tag:
                return

    def _send(self, tag: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(tag + struct.pack(">I", len(payload) + 4) + payload)

    # -- simple protocol -------------------------------------------------

    def query(self, sql: str):
        """Simple query; returns (columns, rows, tags, errors)."""
        self._send(b"Q", sql.encode() + b"\x00")
        cols: List[str] = []
        rows: List[list] = []
        tags: List[str] = []
        errors: List[str] = []
        self.last_error_codes = []
        for tag, payload in self._messages_until(b"Z"):
            if tag == b"T":
                cols = self._parse_rowdesc(payload)
            elif tag == b"D":
                rows.append(self._parse_datarow(payload))
            elif tag == b"C":
                tags.append(payload.rstrip(b"\x00").decode())
            elif tag == b"E":
                errors.append(self._parse_error(payload))
                self.last_error_codes.append(
                    self._parse_error_fields(payload).get("C", "")
                )
            elif tag == b"Z":
                self.txn_status = payload.decode()
        return cols, rows, tags, errors

    @staticmethod
    def cancel_request(host: str, port: int, key: Tuple[int, int]) -> None:
        """Fire a CancelRequest on its own connection (libpq shape)."""
        s = socket.create_connection((host, port), timeout=10.0)
        try:
            s.sendall(struct.pack(">IIII", 16, 80877102, *key))
        finally:
            s.close()

    # -- extended protocol -----------------------------------------------

    def prepared(self, sql: str, params: Tuple = (),
                 param_oids: Tuple = (), binary: bool = False):
        """Parse/Bind/Execute/Sync round; returns (cols, rows, tag, err).

        With ``param_oids`` the Parse message declares each parameter's
        type (what psycopg does for typed Python values); with
        ``binary`` params are sent in binary format for their OID.
        """
        parse = b"\x00" + sql.encode() + b"\x00"
        parse += struct.pack(">h", len(param_oids))
        for oid in param_oids:
            parse += struct.pack(">I", oid)
        self._send(b"P", parse)
        if binary:
            bind = b"\x00\x00" + struct.pack(">hh", 1, 1)  # all binary
        else:
            bind = b"\x00\x00" + struct.pack(">h", 0)
        bind += struct.pack(">h", len(params))
        for i, p in enumerate(params):
            if p is None:
                bind += struct.pack(">i", -1)
            elif binary:
                oid = param_oids[i] if i < len(param_oids) else 0
                s = self._encode_binary(p, oid)
                bind += struct.pack(">i", len(s)) + s
            else:
                s = str(p).encode()
                bind += struct.pack(">i", len(s)) + s
        bind += struct.pack(">h", 0)
        self._send(b"B", bind)
        self._send(b"D", b"P\x00")
        self._send(b"E", b"\x00" + struct.pack(">i", 0))
        self._send(b"S")
        cols: List[str] = []
        self.col_oids: List[int] = []
        rows: List[list] = []
        tag_out: Optional[str] = None
        err: Optional[str] = None
        for tag, payload in self._messages_until(b"Z"):
            if tag == b"T":
                cols = self._parse_rowdesc(payload)
                self.col_oids = self._parse_rowdesc_oids(payload)
            elif tag == b"D" and len(payload) >= 2:
                rows.append(self._parse_datarow(payload))
            elif tag == b"C":
                tag_out = payload.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = self._parse_error(payload)
            elif tag == b"Z":
                self.txn_status = payload.decode()
        return cols, rows, tag_out, err

    def execute_limited(self, sql: str, max_rows: int,
                        rounds: int = 10):
        """Parse/Bind once, then Execute with a row limit repeatedly
        until CommandComplete — exercising PortalSuspended ('s').
        Returns (rows_per_round, suspensions, final_tag, err)."""
        self._send(b"P", b"\x00" + sql.encode() + b"\x00"
                   + struct.pack(">h", 0))
        self._send(b"B", b"\x00\x00" + struct.pack(">h", 0)
                   + struct.pack(">h", 0) + struct.pack(">h", 0))
        self._send(b"D", b"P\x00")
        rows_per_round: List[int] = []
        suspensions = 0
        final_tag: Optional[str] = None
        err: Optional[str] = None
        for _ in range(rounds):
            self._send(b"E", b"\x00" + struct.pack(">i", max_rows))
            self._send(b"H")  # flush
            count = 0
            done = False
            while True:
                tag, payload = self._recv_msg()
                if tag == b"D":
                    count += 1
                elif tag == b"s":
                    suspensions += 1
                    break
                elif tag == b"C":
                    final_tag = payload.rstrip(b"\x00").decode()
                    done = True
                    break
                elif tag == b"E":
                    err = self._parse_error(payload)
                    done = True
                    break
            rows_per_round.append(count)
            if done:
                break
        self._send(b"S")
        for tag, payload in self._messages_until(b"Z"):
            pass
        return rows_per_round, suspensions, final_tag, err

    def typed_query(self, sql: str, params: Tuple = (),
                    param_oids: Tuple = (), binary: bool = False):
        """prepared() + decode each result cell by its column OID, the
        way a real typed driver (psycopg) consumes text-format results."""
        cols, rows, tag, err = self.prepared(sql, params, param_oids, binary)
        if err:
            return cols, rows, tag, err
        decoded = [
            tuple(
                self._decode_text(v, oid)
                for v, oid in zip(row, self.col_oids)
            )
            for row in rows
        ]
        return cols, decoded, tag, err

    @staticmethod
    def _encode_binary(p, oid: int) -> bytes:
        if oid in (21,):
            return struct.pack(">h", p)
        if oid in (23,):
            return struct.pack(">i", p)
        if oid in (20,):
            return struct.pack(">q", p)
        if oid == 700:
            return struct.pack(">f", p)
        if oid == 701:
            return struct.pack(">d", p)
        if oid == 16:
            return b"\x01" if p else b"\x00"
        if oid == 17:
            return bytes(p)
        return str(p).encode()

    @staticmethod
    def _decode_text(v, oid: int):
        if v is None:
            return None
        if oid in (20, 21, 23):
            return int(v)
        if oid in (700, 701):
            return float(v)
        if oid == 16:
            return v in ("t", "true", "1")
        if oid == 17:
            return bytes.fromhex(v[2:]) if v.startswith("\\x") else v.encode()
        return v

    # -- parsing ---------------------------------------------------------

    @staticmethod
    def _parse_rowdesc(payload: bytes) -> List[str]:
        (n,) = struct.unpack_from(">h", payload, 0)
        cols = []
        pos = 2
        for _ in range(n):
            end = payload.index(b"\x00", pos)
            cols.append(payload[pos:end].decode())
            pos = end + 1 + 18
        return cols

    @staticmethod
    def _parse_datarow(payload: bytes) -> list:
        (n,) = struct.unpack_from(">h", payload, 0)
        pos = 2
        out = []
        for _ in range(n):
            (ln,) = struct.unpack_from(">i", payload, pos)
            pos += 4
            if ln == -1:
                out.append(None)
            else:
                out.append(payload[pos : pos + ln].decode())
                pos += ln
        return out

    @staticmethod
    def _parse_rowdesc_oids(payload: bytes) -> List[int]:
        (n,) = struct.unpack_from(">h", payload, 0)
        oids = []
        pos = 2
        for _ in range(n):
            end = payload.index(b"\x00", pos)
            pos = end + 1
            # table oid (4) + attnum (2), then the type OID
            (oid,) = struct.unpack_from(">I", payload, pos + 6)
            oids.append(oid)
            pos += 18
        return oids

    @staticmethod
    def _parse_error(payload: bytes) -> str:
        return PgClient._parse_error_fields(payload).get(
            "M", "unknown error"
        )

    @staticmethod
    def _parse_error_fields(payload: bytes) -> dict:
        fields = {}
        for part in payload.split(b"\x00"):
            if part:
                fields[chr(part[0])] = part[1:].decode()
        return fields

    def close(self) -> None:
        try:
            self._send(b"X")
        except OSError:
            pass
        self.sock.close()
