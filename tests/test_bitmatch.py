"""Bit-match: real agents under the discrete-event scheduler vs the
simulator's deterministic replay (BASELINE north star, exactness half).

The sides share only the per-node PRNG streams and the tick-backoff
mapping; infected sets and per-node message counts are computed
independently (agents: storage/bookkeeping/wire pipeline; sim: array
state machine) and must agree tick for tick.
"""

from corrosion_tpu.agent.det import DetCluster, DetParams, run_det_epidemic
from corrosion_tpu.sim.bitmatch import (
    det_sim_epidemic,
    diff_det_traces,
    run_bitmatch,
)


def test_bitmatch_small_cluster(tmp_path):
    r = run_bitmatch(16, writes=2, seed=3, base_dir=str(tmp_path))
    assert r["bitmatch"], r
    for w in r["per_write"]:
        assert w["converged_tick_sim"] == w["converged_tick_agents"]
        assert w["first_mismatch_tick"] is None


def test_bitmatch_n64(tmp_path):
    """The north-star comparison shape at reduced N (the driver-scale
    N=256 runs in bench.py; same code path)."""
    r = run_bitmatch(64, writes=2, seed=0, base_dir=str(tmp_path))
    assert r["bitmatch"], r
    # every node exhausted its budget: total msgs = N * fanout * max_tx
    assert r["per_write"][0]["msgs_total"] == 64 * 3 * 5


def test_bitmatch_detects_divergence(tmp_path):
    """Negative control: a semantic difference (changed backoff) must
    surface as a per-tick mismatch, proving the diff has teeth."""
    params = DetParams(n_nodes=16, seed=1, backoff_ticks=2.5)
    cluster = DetCluster(params, base_dir=str(tmp_path))
    try:
        agents_trace = run_det_epidemic(cluster, origin=0, write_id=0)
    finally:
        cluster.close()
    skewed = DetParams(n_nodes=16, seed=1, backoff_ticks=1.0)
    sim_trace = det_sim_epidemic(skewed, origin=0)
    d = diff_det_traces(sim_trace, agents_trace)
    assert not d["match"]
    assert d["first_mismatch_tick"] is not None


def test_bitmatch_seed_sensitivity(tmp_path):
    """A different seed still bit-matches (the equality is not an
    artifact of one lucky stream)."""
    r = run_bitmatch(16, writes=1, seed=7, base_dir=str(tmp_path))
    assert r["bitmatch"], r


def test_bitmatch_across_parameter_grid(tmp_path):
    """The equality is not an artifact of one parameter point: vary
    fanout, retransmission budget, and backoff — every combination
    must still match tick for tick."""
    grid = [
        dict(fanout=2, max_transmissions=3, backoff_ticks=1.0),
        dict(fanout=5, max_transmissions=8, backoff_ticks=0.0),
        dict(fanout=3, max_transmissions=5, backoff_ticks=4.0),
    ]
    for i, params in enumerate(grid):
        (tmp_path / f"g{i}").mkdir()
        r = run_bitmatch(
            24, writes=1, seed=i,
            base_dir=str(tmp_path / f"g{i}"), **params,
        )
        assert r["bitmatch"], (params, r)


def test_det_sim_trace_differs_across_seeds():
    """The PRNG wiring is live, not vacuous: different seeds give
    different delivery schedules."""
    a = det_sim_epidemic(DetParams(n_nodes=16, seed=0), origin=0)
    b = det_sim_epidemic(DetParams(n_nodes=16, seed=7), origin=0)
    assert a["ticks"] != b["ticks"]


# -- the HEADLINE protocol shape: ring0 + loss + anti-entropy sync -----


def test_bitmatch_headline_protocol(tmp_path):
    """The north-star clause: the exactness proof covers the SAME
    protocol the benchmark runs — ring0-first fanout, 5% per-message
    loss, anti-entropy sync every 8 ticks — not a simplified one.
    Infected sets, per-node broadcast msgs AND per-node sync msgs must
    be equal tick for tick, across two writes with carried-over PRNG,
    last-sync and tick-offset state."""
    r = run_bitmatch(
        32, writes=2, seed=0, loss=0.05, ring0_size=8, sync_interval=8,
        base_dir=str(tmp_path),
    )
    assert r["bitmatch"], r
    for w in r["per_write"]:
        assert w["converged_tick_sim"] == w["converged_tick_agents"]
        assert w["first_mismatch_tick"] is None
    # sync traffic actually flowed (handshakes at minimum)
    assert all(w["sync_msgs_total"] > 0 for w in r["per_write"])


def test_bitmatch_headline_loss_actually_drops(tmp_path):
    """With heavy loss and NO sync, coverage at quiescence falls short
    of N on some seeds — proving the loss mask is live on the agent
    side (not silently ignored) while both sides still bit-match."""
    short = dict(writes=1, fanout=2, max_transmissions=2)
    orphaned = False
    for seed in range(4):
        (tmp_path / f"s{seed}").mkdir()
        r = run_bitmatch(
            24, seed=seed, loss=0.6, base_dir=str(tmp_path / f"s{seed}"),
            **short,
        )
        assert r["bitmatch"], (seed, r)
        if r["per_write"][0]["converged_tick_agents"] is None:
            orphaned = True
    assert orphaned, "60% loss never orphaned a node — loss mask dead?"


def test_bitmatch_sync_heals_loss_orphans(tmp_path):
    """Same heavy-loss shape WITH sync: every epidemic now converges
    (anti-entropy heals what loss dropped), and the traces still match
    exactly — pinning the det sync round against the sim's replay."""
    for seed in range(2):
        (tmp_path / f"s{seed}").mkdir()
        r = run_bitmatch(
            24, writes=1, seed=seed, loss=0.6, fanout=2,
            max_transmissions=2, sync_interval=4,
            base_dir=str(tmp_path / f"s{seed}"),
        )
        assert r["bitmatch"], (seed, r)
        assert r["per_write"][0]["converged_tick_agents"] is not None


def test_bitmatch_detects_loss_skew(tmp_path):
    """Negative control for the headline shape: a loss-rate difference
    desynchronizes the delivery schedule and must surface as a
    mismatch."""
    params = DetParams(n_nodes=24, seed=2, loss=0.05, ring0_size=8,
                       sync_interval=8)
    cluster = DetCluster(params, base_dir=str(tmp_path))
    try:
        agents_trace = run_det_epidemic(cluster, origin=0, write_id=0)
    finally:
        cluster.close()
    skewed = DetParams(n_nodes=24, seed=2, loss=0.25, ring0_size=8,
                       sync_interval=8)
    sim_trace = det_sim_epidemic(skewed, origin=0)
    d = diff_det_traces(sim_trace, agents_trace)
    assert not d["match"]


def test_bitmatch_detects_sync_skew(tmp_path):
    """Negative control: replaying with a different sync cadence must
    mismatch (sync msgs diverge at the first differing sync tick)."""
    params = DetParams(n_nodes=24, seed=0, loss=0.3, sync_interval=4)
    cluster = DetCluster(params, base_dir=str(tmp_path))
    try:
        agents_trace = run_det_epidemic(cluster, origin=0, write_id=0)
    finally:
        cluster.close()
    skewed = DetParams(n_nodes=24, seed=0, loss=0.3, sync_interval=6)
    sim_trace = det_sim_epidemic(skewed, origin=0)
    d = diff_det_traces(sim_trace, agents_trace)
    assert not d["match"]


def test_bitmatch_headline_single_sync_peer(tmp_path):
    """The benchmarked kernel syncs with ONE peer per round
    (sync_peers=1); the bit-match holds at that exact shape too, not
    only at the agent default of 3."""
    r = run_bitmatch(
        32, writes=1, seed=4, loss=0.05, ring0_size=8, sync_interval=8,
        sync_peers=1, base_dir=str(tmp_path),
    )
    assert r["bitmatch"], r
    assert r["per_write"][0]["converged_tick_agents"] is not None
