"""Bit-match: real agents under the discrete-event scheduler vs the
simulator's deterministic replay (BASELINE north star, exactness half).

The sides share only the per-node PRNG streams and the tick-backoff
mapping; infected sets and per-node message counts are computed
independently (agents: storage/bookkeeping/wire pipeline; sim: array
state machine) and must agree tick for tick.
"""

from corrosion_tpu.agent.det import DetCluster, DetParams, run_det_epidemic
from corrosion_tpu.sim.bitmatch import (
    det_sim_epidemic,
    diff_det_traces,
    run_bitmatch,
)


def test_bitmatch_small_cluster(tmp_path):
    r = run_bitmatch(16, writes=2, seed=3, base_dir=str(tmp_path))
    assert r["bitmatch"], r
    for w in r["per_write"]:
        assert w["converged_tick_sim"] == w["converged_tick_agents"]
        assert w["first_mismatch_tick"] is None


def test_bitmatch_n64(tmp_path):
    """The north-star comparison shape at reduced N (the driver-scale
    N=256 runs in bench.py; same code path)."""
    r = run_bitmatch(64, writes=2, seed=0, base_dir=str(tmp_path))
    assert r["bitmatch"], r
    # every node exhausted its budget: total msgs = N * fanout * max_tx
    assert r["per_write"][0]["msgs_total"] == 64 * 3 * 5


def test_bitmatch_detects_divergence(tmp_path):
    """Negative control: a semantic difference (changed backoff) must
    surface as a per-tick mismatch, proving the diff has teeth."""
    params = DetParams(n_nodes=16, seed=1, backoff_ticks=2.5)
    cluster = DetCluster(params, base_dir=str(tmp_path))
    try:
        agents_trace = run_det_epidemic(cluster, origin=0, write_id=0)
    finally:
        cluster.close()
    skewed = DetParams(n_nodes=16, seed=1, backoff_ticks=1.0)
    sim_trace = det_sim_epidemic(skewed, origin=0)
    d = diff_det_traces(sim_trace, agents_trace)
    assert not d["match"]
    assert d["first_mismatch_tick"] is not None


def test_bitmatch_seed_sensitivity(tmp_path):
    """A different seed still bit-matches (the equality is not an
    artifact of one lucky stream)."""
    r = run_bitmatch(16, writes=1, seed=7, base_dir=str(tmp_path))
    assert r["bitmatch"], r


def test_bitmatch_across_parameter_grid(tmp_path):
    """The equality is not an artifact of one parameter point: vary
    fanout, retransmission budget, and backoff — every combination
    must still match tick for tick."""
    grid = [
        dict(fanout=2, max_transmissions=3, backoff_ticks=1.0),
        dict(fanout=5, max_transmissions=8, backoff_ticks=0.0),
        dict(fanout=3, max_transmissions=5, backoff_ticks=4.0),
    ]
    for i, params in enumerate(grid):
        (tmp_path / f"g{i}").mkdir()
        r = run_bitmatch(
            24, writes=1, seed=i,
            base_dir=str(tmp_path / f"g{i}"), **params,
        )
        assert r["bitmatch"], (params, r)


def test_det_sim_trace_differs_across_seeds():
    """The PRNG wiring is live, not vacuous: different seeds give
    different delivery schedules."""
    a = det_sim_epidemic(DetParams(n_nodes=16, seed=0), origin=0)
    b = det_sim_epidemic(DetParams(n_nodes=16, seed=7), origin=0)
    assert a["ticks"] != b["ticks"]
