"""SWIM over the foca binary wire (bridge/foca.py + agent/swim_foca.py).

The foreign-peer tests speak nothing but raw foca datagram bytes over a
plain UDP socket — no agent-side helpers on the "remote" end — and
drive the full membership cycle against a live agent: join (Announce →
Feed), being probed (Ping → Ack), and suspicion refutation (gossiped
Suspect → incarnation bump).  This is the cluster-level counterpart of
``tests/test_live_wire.py``'s broadcast/sync byte pinning.
"""

import asyncio
import socket

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from corrosion_tpu.bridge import foca
from corrosion_tpu.bridge.bincode import BReader, BWriter

NIL = b"\x00" * 16


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


# -- bincode primitives ------------------------------------------------


def test_bincode_varint_layout():
    w = BWriter()
    for v in (0, 1, 250):
        assert BWriter().varint(v).getvalue() == bytes((v,))
    assert BWriter().varint(251).getvalue() == b"\xfb\xfb\x00"
    assert BWriter().varint(7777).getvalue() == b"\xfb\x61\x1e"
    assert BWriter().varint(70_000).getvalue() == b"\xfc\x70\x11\x01\x00"
    assert BWriter().varint(2**40).getvalue() == (
        b"\xfd\x00\x00\x00\x00\x00\x01\x00\x00"
    )
    for v in (0, 250, 251, 65535, 65536, 2**32, 2**63):
        r = BReader(BWriter().varint(v).getvalue())
        assert r.varint() == v and r.remaining() == 0


def test_bincode_signed_zigzag():
    for v in (0, -1, 1, -126, 300, -40000, 2**40, -(2**40)):
        r = BReader(BWriter().signed_varint(v).getvalue())
        assert r.signed_varint() == v


# -- foca codec golden bytes ------------------------------------------


def _actor(ident=b"\xaa" * 16, addr=("127.0.0.1", 7777), ts=5, cid=0):
    return foca.FocaActor(id=ident, addr=addr, ts=ts, cluster_id=cid)


def test_actor_golden_bytes():
    """Pin the Actor layout: uuid serialize_bytes + SocketAddr enum +
    NTP64 varint + ClusterId varint (actor.rs:132-139 serde order)."""
    w = BWriter()
    foca._w_actor(w, _actor())
    assert w.getvalue() == (
        b"\x10" + b"\xaa" * 16          # uuid: len 16 + bytes
        + b"\x00" + b"\x7f\x00\x00\x01"  # V4 tag + octets
        + b"\xfb\x61\x1e"                # port 7777
        + b"\x05"                        # ts
        + b"\x00"                        # cluster_id
    )


def test_datagram_golden_bytes_ping():
    d = foca.FocaDatagram(
        src=_actor(), src_incarnation=2,
        dst=_actor(ident=b"\xbb" * 16, addr=("10.0.0.9", 80), ts=0),
        message=foca.FocaMessage(tag=foca.PING, probe_number=300),
        updates=[],
    )
    enc = foca.encode_datagram(d)
    assert enc == (
        b"\x10" + b"\xaa" * 16 + b"\x00\x7f\x00\x00\x01\xfb\x61\x1e\x05\x00"
        + b"\x02"                        # src_incarnation
        + b"\x10" + b"\xbb" * 16 + b"\x00\x0a\x00\x00\x09\x50\x00\x00"
        + b"\x00"                        # Message tag 0 = Ping
        + b"\xfb\x2c\x01"                # probe number 300
    )
    rt = foca.decode_datagram(enc)
    assert rt == d


def test_datagram_roundtrip_all_messages():
    peer = _actor(ident=b"\xcc" * 16, addr=("::1", 9000), ts=9, cid=3)
    src = _actor(cid=3)
    dst = _actor(ident=b"\xbb" * 16, cid=3)
    msgs = [
        foca.FocaMessage(tag=foca.PING, probe_number=7),
        foca.FocaMessage(tag=foca.ACK, probe_number=65535),
        foca.FocaMessage(tag=foca.PING_REQ, peer=peer, probe_number=1),
        foca.FocaMessage(tag=foca.INDIRECT_PING, peer=peer, probe_number=2),
        foca.FocaMessage(tag=foca.INDIRECT_ACK, peer=peer, probe_number=3),
        foca.FocaMessage(tag=foca.FORWARDED_ACK, peer=peer, probe_number=4),
        foca.FocaMessage(tag=foca.ANNOUNCE),
        foca.FocaMessage(tag=foca.FEED),
        foca.FocaMessage(tag=foca.GOSSIP),
        foca.FocaMessage(tag=foca.TURN_UNDEAD),
    ]
    updates = [
        foca.FocaMember(actor=peer, incarnation=4, state=foca.STATE_SUSPECT),
        foca.FocaMember(actor=src, incarnation=0, state=foca.STATE_ALIVE),
    ]
    for m in msgs:
        d = foca.FocaDatagram(
            src=src, src_incarnation=1, dst=dst, message=m, updates=updates
        )
        assert foca.decode_datagram(foca.encode_datagram(d)) == d


def test_datagram_update_fill_respects_packet_cap():
    src = _actor()
    dst = _actor(ident=b"\xbb" * 16)
    many = [
        foca.FocaMember(
            actor=_actor(ident=bytes((i % 256,)) * 16),
            incarnation=i, state=foca.STATE_ALIVE,
        )
        for i in range(200)
    ]
    d = foca.FocaDatagram(
        src=src, src_incarnation=0, dst=dst,
        message=foca.FocaMessage(tag=foca.GOSSIP), updates=many,
    )
    enc = foca.encode_datagram(d)
    assert len(enc) <= foca.MAX_PACKET
    got = foca.decode_datagram(enc)
    assert 0 < len(got.updates) < 200  # filled to the cap, then stopped


# -- live foreign peer -------------------------------------------------


class _ForeignPeer:
    """A 'reference' node: raw UDP socket + bridge/foca.py bytes only."""

    def __init__(self, ident: bytes, cluster_id: int = 0):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.setblocking(False)
        self.me = foca.FocaActor(
            id=ident, addr=self.sock.getsockname()[:2], ts=1,
            cluster_id=cluster_id,
        )
        self.incarnation = 0

    def send(self, addr, dst, message, updates=()):
        d = foca.FocaDatagram(
            src=self.me, src_incarnation=self.incarnation, dst=dst,
            message=message, updates=list(updates),
        )
        self.sock.sendto(foca.encode_datagram(d), tuple(addr))

    async def recv(self, want_tag=None, timeout=5.0):
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                raise TimeoutError(f"no datagram (want tag {want_tag})")
            data = await asyncio.wait_for(
                loop.sock_recv(self.sock, 2048), timeout=remaining
            )
            d = foca.decode_datagram(data)
            if want_tag is None or d.message.tag == want_tag:
                return d

    def close(self):
        self.sock.close()


def test_foreign_peer_joins_is_probed_and_sees_refutation(run, tmp_path):
    """The VERDICT-r3 cluster claim: a peer speaking only reference
    bytes (1) joins via Announce and gets a Feed, (2) is probed and its
    Ack is accepted (it stays ALIVE), (3) gossips a Suspect rumor about
    the agent and sees the refutation (bumped incarnation) come back."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        peer = _ForeignPeer(b"\xee" * 16)
        try:
            # -- join ---------------------------------------------------
            peer.send(
                a.gossip_addr,
                foca.FocaActor(id=NIL, addr=tuple(a.gossip_addr), ts=0,
                               cluster_id=0),
                foca.FocaMessage(tag=foca.ANNOUNCE),
            )
            feed = await peer.recv(want_tag=foca.FEED)
            agent_identity = feed.src
            assert agent_identity.id == a.actor_id
            assert any(u.actor.id == a.actor_id for u in feed.updates)
            # the agent now sees us as a member
            await wait_for(
                lambda: any(
                    m.actor_id == peer.me.id for m in a.members.alive()
                )
            )

            # -- probed -------------------------------------------------
            ping = await peer.recv(want_tag=foca.PING)
            base_inc = ping.src_incarnation

            def ack(p):
                peer.send(
                    a.gossip_addr, agent_identity,
                    foca.FocaMessage(
                        tag=foca.ACK,
                        probe_number=p.message.probe_number,
                    ),
                )

            ack(ping)
            # keep answering probes for a few cycles: acks accepted =
            # we stay ALIVE
            deadline = asyncio.get_running_loop().time() + (
                a.config.probe_interval * 4
            )
            while asyncio.get_running_loop().time() < deadline:
                try:
                    ack(await peer.recv(want_tag=foca.PING, timeout=0.2))
                except TimeoutError:
                    pass
            me = a.members.get(peer.me.id)
            assert me is not None and me.state.value == "alive"

            # -- refutation ---------------------------------------------
            peer.send(
                a.gossip_addr, agent_identity,
                foca.FocaMessage(tag=foca.GOSSIP),
                updates=[foca.FocaMember(
                    actor=agent_identity,
                    incarnation=base_inc,
                    state=foca.STATE_SUSPECT,
                )],
            )
            await wait_for(lambda: a.incarnation > base_inc)
            # and the refutation reaches the wire: the agent's next
            # datagram to us carries its self entry above the rumor
            # (drain any pings that predate the bump)
            deadline = asyncio.get_running_loop().time() + 5.0
            while True:
                ping2 = await peer.recv(want_tag=foca.PING)
                if ping2.src_incarnation > base_inc:
                    break
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("no refuted ping arrived")
            selfs = [u for u in ping2.updates if u.actor.id == a.actor_id]
            assert selfs and selfs[0].incarnation > base_inc
            assert selfs[0].state == foca.STATE_ALIVE
        finally:
            peer.close()
            await a.stop()

    run(main())


def test_foreign_cluster_peer_is_rejected(run, tmp_path):
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        peer = _ForeignPeer(b"\xdd" * 16, cluster_id=9)
        try:
            peer.send(
                a.gossip_addr,
                foca.FocaActor(id=NIL, addr=tuple(a.gossip_addr), ts=0,
                               cluster_id=9),
                foca.FocaMessage(tag=foca.ANNOUNCE),
            )
            with pytest.raises(TimeoutError):
                await peer.recv(want_tag=foca.FEED, timeout=0.8)
            assert all(
                m.actor_id != peer.me.id for m in a.members.all()
            )
        finally:
            peer.close()
            await a.stop()

    run(main())


def test_hostname_bootstrap_joins_on_foca_wire(run, tmp_path):
    """A bootstrap entry spelled differently from the receiver's bound
    addr (hostname vs numeric) must still join: nil-id announce dsts
    are accepted by arrival, not by literal addr equality."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()

    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"))
        b = await launch_test_agent(
            tmpdir=str(tmp_path / "b"),
            bootstrap=[f"localhost:{a.gossip_addr[1]}"],
        )
        try:
            await wait_for(
                lambda: any(
                    m.actor_id == b.actor_id for m in a.members.alive()
                ) and any(
                    m.actor_id == a.actor_id for m in b.members.alive()
                ),
                timeout=10,
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_turn_undead_renews_identity(run, tmp_path):
    """A down-marked node that keeps talking gets TurnUndead and
    renews: fresh identity ts + bumped incarnation + re-announce."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        peer = _ForeignPeer(b"\xcd" * 16)
        try:
            # join, then gossip ourselves DOWN at our own incarnation
            peer.send(
                a.gossip_addr,
                foca.FocaActor(id=NIL, addr=tuple(a.gossip_addr), ts=0,
                               cluster_id=0),
                foca.FocaMessage(tag=foca.ANNOUNCE),
            )
            feed = await peer.recv(want_tag=foca.FEED)
            agent_identity = feed.src
            peer.send(
                a.gossip_addr, agent_identity,
                foca.FocaMessage(tag=foca.GOSSIP),
                updates=[foca.FocaMember(
                    actor=peer.me, incarnation=peer.incarnation,
                    state=foca.STATE_DOWN,
                )],
            )
            await wait_for(
                lambda: (m := a.members.get(peer.me.id)) is not None
                and m.state.value == "down"
            )
            # talk again at the SAME identity: the agent answers
            # TurnUndead instead of reviving us
            peer.send(
                a.gossip_addr, agent_identity,
                foca.FocaMessage(tag=foca.PING, probe_number=42),
            )
            tu = await peer.recv(want_tag=foca.TURN_UNDEAD)
            assert tu.src.id == a.actor_id
            # renew: new identity generation (newer ts) revives us
            peer.me = foca.FocaActor(
                id=peer.me.id, addr=peer.me.addr, ts=peer.me.ts + 10,
                cluster_id=0,
            )
            peer.send(
                a.gossip_addr, agent_identity,
                foca.FocaMessage(tag=foca.GOSSIP),
            )
            await wait_for(
                lambda: (m := a.members.get(peer.me.id)) is not None
                and m.state.value == "alive"
            )
        finally:
            peer.close()
            await a.stop()

    run(main())


def test_periodic_gossip_spreads_without_probing(run, tmp_path):
    """foca periodic_gossip parity: with probing quiesced, a membership
    update still disseminates on the dedicated gossip cadence; once the
    backlog decays, a quiet cluster sends zero gossip datagrams."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()

    async def main():
        from corrosion_tpu.agent.members import MemberState

        common = dict(probe_interval=3600.0, gossip_interval=0.05)
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"), **common)
        b = await launch_test_agent(
            tmpdir=str(tmp_path / "b"),
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
            **common,
        )
        try:
            await wait_for(
                lambda: a.members.alive() and b.members.alive(), timeout=10
            )
            # plant a third-party SUSPECT record at a; no probes run, so
            # only the gossip loop can carry it to b
            ghost = b"\x99" * 16
            a.members.upsert(ghost, ("127.0.0.1", 9), MemberState.SUSPECT, 3)
            a._swim_update_tx[ghost] = 0
            await wait_for(
                lambda: (m := b.members.get(ghost)) is not None
                and m.state is MemberState.SUSPECT
                and m.incarnation == 3,
                timeout=10,
            )
            # decay: once every entry exhausts its retransmit budget the
            # loop goes silent (skip rounds entirely)
            sent_before = a.metrics.get_counter_sum(
                "corro_gossip_datagrams_sent_total"
            )
            await asyncio.sleep(1.0)
            mid = a.metrics.get_counter_sum(
                "corro_gossip_datagrams_sent_total")
            await asyncio.sleep(0.5)
            late = a.metrics.get_counter_sum(
                "corro_gossip_datagrams_sent_total")
            assert late == mid, "quiet cluster must stop gossiping"
            assert sent_before > 0
        finally:
            await b.stop()
            await a.stop()

    run(main())
