"""Sync protocol depth: peer choice, cross-peer dedup, concurrent need
jobs, adaptive chunking + slow-peer abort.

Parity: ``crates/corro-agent/src/api/peer.rs:344-348,796-811,836-844,
1240-1371`` and ``agent/handlers.rs:963-1074``.
"""

import asyncio

import pytest

from corrosion_tpu.agent.members import Member, MemberState
from corrosion_tpu.agent.runtime import STREAM_BI
from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import ActorId, SyncNeedV1, Timestamp
from corrosion_tpu.types.actor import ClusterId
from corrosion_tpu.types.payload import BiPayload

QUIET = dict(
    sync_interval_min=3600.0,
    sync_interval_max=7200.0,
    probe_interval=3600.0,
    maintenance_interval=3600.0,
)


def _member_for(agent) -> Member:
    return Member(actor_id=agent.actor_id, addr=tuple(agent.gossip_addr))


def test_parallel_sync_serves_disjoint_halves(tmp_path):
    """Two peers holding the same 40 versions each serve roughly half of
    a fresh node's needs (round-robin allocation + cross-peer dedup)."""
    async def main():
        (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
        (tmp_path / "c").mkdir(); (tmp_path / "d").mkdir()
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"))
        # >100 versions: the round-robin allocator drains 10 needs/turn,
        # each need a 10-version chunk, so 120 versions = 12 chunk-needs
        # — the first server takes 10, the second the rest
        for i in range(120):
            a.execute_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)",
                  (i, f"v{i}"))]
            )
        boot = [f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        b = await launch_test_agent(bootstrap=boot, tmpdir=str(tmp_path / "b"))
        c = await launch_test_agent(bootstrap=boot, tmpdir=str(tmp_path / "c"))

        def caught_up(x):
            return x.bookie.for_actor(a.actor_id).contains_range(1, 120)

        await wait_for(lambda: caught_up(b) and caught_up(c), timeout=30)

        # fresh node that only knows b and c — NOT the origin
        d = await launch_test_agent(tmpdir=str(tmp_path / "d"), **QUIET)
        d.members.upsert(b.actor_id, tuple(b.gossip_addr))
        d.members.upsert(c.actor_id, tuple(c.gossip_addr))
        served_before = {
            x.actor_id: int(x.metrics.get_counter("corro_sync_served_total") or 0)
            for x in (b, c)
        }
        n = await d.parallel_sync(
            [_member_for(b), _member_for(c)]
        )
        assert n > 0
        await wait_for(lambda: caught_up(d), timeout=20)
        served = {
            x.actor_id: int(x.metrics.get_counter("corro_sync_served_total") or 0)
            - served_before[x.actor_id]
            for x in (b, c)
        }
        # BOTH peers served a share (not one peer serving everything)
        assert served[b.actor_id] > 0, served
        assert served[c.actor_id] > 0, served
        for x in (a, b, c, d):
            await x.stop()

    asyncio.run(main())


async def _open_sync_session(a, rcvbuf=None):
    """Raw-socket sync client: SyncStart + Clock + request-everything."""
    import socket

    h, p = a.gossip_addr
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf is not None:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
    sock.setblocking(False)
    await asyncio.get_running_loop().sock_connect(sock, (h, p))
    reader, writer = await asyncio.open_connection(sock=sock, limit=2**16)
    writer.write(STREAM_BI)
    writer.write(
        speedy.frame(
            speedy.encode_bi_payload(
                BiPayload(actor_id=ActorId(b"\xbb" * 16)), ClusterId(0)
            )
        )
    )
    writer.write(speedy.frame(speedy.encode_sync_message(Timestamp(1))))
    req = [(ActorId(a.actor_id), [SyncNeedV1.full(1, 1)])]
    writer.write(speedy.frame(speedy.encode_sync_message(("request", req))))
    await writer.drain()
    writer.write_eof()
    return reader, writer


def _big_write(a, rows: int, width: int) -> None:
    big = "x" * width
    a.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, big))
         for i in range(rows)]
    )


def test_slow_reader_triggers_abort(tmp_path):
    """A client that requests everything and never reads trips the
    slow-peer abort once the socket buffers fill (peer.rs:796-800)."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path), **QUIET)
        _big_write(a, 4000, 2048)  # ~8 MB to serve
        a.SYNC_SLOW_ABORT = 0.4
        reader, writer = await _open_sync_session(a, rcvbuf=4096)
        # do NOT read: the server's sends back up until drain stalls
        await wait_for(
            lambda: a.metrics.get_counter(
                "corro_sync_slow_peer_aborts_total"
            ),
            timeout=30,
        )
        writer.close()
        await a.stop()

    asyncio.run(main())


def test_slow_reader_triggers_chunk_halving(tmp_path):
    """A trickling reader drives the server's adaptive chunk size down
    (8 KiB halving toward the 1 KiB floor, peer.rs:344-348,801-811)."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path), **QUIET)
        # must exceed the kernel's auto-tuned send buffer or drains
        # never block and the server finishes before adapting
        _big_write(a, 4000, 2048)  # ~8 MB to serve
        a.SYNC_ADAPT_THRESHOLD = 0.02
        a.SYNC_SLOW_ABORT = 30.0
        reader, writer = await _open_sync_session(a, rcvbuf=4096)
        # trickle-read so drains are slow but never fully stall
        for _ in range(400):
            try:
                await asyncio.wait_for(reader.read(2048), timeout=1.0)
            except asyncio.TimeoutError:
                pass
            if a.metrics.get_counter("corro_sync_chunk_halvings_total"):
                break
            await asyncio.sleep(0.05)
        assert a.metrics.get_counter("corro_sync_chunk_halvings_total")
        writer.close()
        await a.stop()

    asyncio.run(main())


def test_peer_choice_prefers_needed_stale_and_close(tmp_path):
    """_choose_sync_peers ranks by (need_len desc, last_sync_ts asc,
    rtt asc) over a 2x random sample (handlers.rs:963-1074)."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path), **QUIET)
        rich = b"\x01" * 16   # we need 50 versions from this actor
        poor = b"\x02" * 16   # nothing needed
        a.members.upsert(rich, ("127.0.0.1", 1001))
        a.members.upsert(poor, ("127.0.0.1", 1002))
        bv = a.bookie.for_actor(rich)
        bv.apply_version(60, 1, 0)  # creates needed gap 1..59
        ours = a.generate_sync()
        assert ours.need_len_for_actor(ActorId(rich)) > 0
        chosen = a._choose_sync_peers(ours)
        assert chosen, "expected peers chosen"
        assert chosen[0].actor_id == rich
        await a.stop()

    asyncio.run(main())
