"""Virtual-time cluster: clock units, campaign smokes, determinism.

The injectable :class:`~corrosion_tpu.clock.Clock` puts every agent
timer behind one seam; :class:`~corrosion_tpu.sim.vcluster.
VirtualCluster` drives real agents through fault campaigns on a
discrete-event heap.  Tier-1 coverage:

* VirtualClock unit behavior (ordering, lateness, jump, run_until);
* one fast campaign cell per fault family at N=64 (seconds of wall
  time — the whole point of the refactor);
* the determinism contract: two runs with the same (seed, FaultPlan,
  campaign) produce BYTE-IDENTICAL flight-recorder event journals and
  identical end-state checksums;
* a small virtual-vs-real parity cell (the N=32 cell ships in
  TIMELINE_N512.json via ``bench.py --timeline --virtual-time``).
"""

import json
import logging

import pytest

from corrosion_tpu.clock import (
    SYSTEM_CLOCK,
    VIRTUAL_EPOCH_NS,
    SystemClock,
    VirtualClock,
)
from corrosion_tpu.faults import CrashEvent, FaultPlan

# the per-node "quarantining" warning is expected output for the
# hostile families; at N=64 it would drown the test log
logging.getLogger("corrosion_tpu.agent.runtime").setLevel(logging.ERROR)


# ---------------------------------------------------------------------------
# VirtualClock units
# ---------------------------------------------------------------------------


def test_virtual_clock_orders_and_ties_by_insertion():
    clk = VirtualClock()
    fired = []
    clk.schedule(2.0, lambda d: fired.append(("b", d)))
    clk.schedule(1.0, lambda d: fired.append(("a", d)))
    clk.schedule(2.0, lambda d: fired.append(("c", d)))  # tie: after b
    while clk.advance():
        pass
    assert fired == [("a", 1.0), ("b", 2.0), ("c", 2.0)]
    assert clk.monotonic() == 2.0


def test_virtual_clock_jump_models_a_stall():
    """A jump moves time WITHOUT running the events inside it: they
    fire late, and the callback can measure its own lateness — the
    loop-stall model the scheduler's stall beat uses."""
    clk = VirtualClock()
    late = []
    clk.schedule(0.10, lambda due: late.append(clk.monotonic() - due))
    clk.jump(0.25)
    clk.advance()
    assert late and abs(late[0] - 0.15) < 1e-9


def test_virtual_clock_run_until_and_cancel():
    clk = VirtualClock()
    fired = []
    ev = clk.schedule(0.5, lambda d: fired.append("cancelled"))
    clk.schedule(0.7, lambda d: fired.append("kept"))
    clk.cancel(ev)
    ran = clk.run_until(1.0)
    assert ran == 1 and fired == ["kept"]
    assert clk.monotonic() == 1.0
    assert clk.pending() == 0


def test_virtual_wall_epoch_is_fixed():
    a, b = VirtualClock(), VirtualClock()
    assert a.wall_ns() == b.wall_ns() == VIRTUAL_EPOCH_NS
    a.jump(1.5)
    assert a.wall_ns() == VIRTUAL_EPOCH_NS + 1_500_000_000
    assert abs(a.wall() - (VIRTUAL_EPOCH_NS / 1e9 + 1.5)) < 1e-6


def test_system_clock_is_the_stdlib():
    import asyncio
    import time

    assert SystemClock.monotonic is time.monotonic
    assert SystemClock.wall is time.time
    assert SystemClock.wall_ns is time.time_ns
    assert SystemClock.sleep is asyncio.sleep
    assert SystemClock.wait_for is asyncio.wait_for
    assert isinstance(SYSTEM_CLOCK, SystemClock)


def test_virtual_clock_sleep_resolves_on_advance():
    import asyncio

    async def main():
        clk = VirtualClock()
        results = []

        async def sleeper():
            await clk.sleep(0.3)
            results.append(clk.monotonic())

        task = asyncio.ensure_future(sleeper())
        await asyncio.sleep(0)  # let the sleeper register its timer
        while clk.advance():
            await asyncio.sleep(0)
        await task
        assert results == [0.3]

    asyncio.run(main())


# ---------------------------------------------------------------------------
# the agent's clock seam: a virtual clock behind a real agent
# ---------------------------------------------------------------------------


def test_agent_quarantine_window_ages_on_injected_clock(tmp_path):
    """``equiv_quarantine_s`` elapses on the INJECTED clock: no real
    time passes, yet advancing the virtual clock expires the verdict —
    the seam the virtual campaigns rely on."""
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.types import ChangeSource

    clk = VirtualClock()
    a = make_offline_agent(
        tmpdir=str(tmp_path), clock=clk, equiv_quarantine_s=5.0
    )
    try:
        peer = EquivocatingPeer(seed=3, now_ns=clk.wall_ns)
        a.members.upsert(peer.actor_id, ("x", 1))
        ca, cb = peer.conflicting_pair(1)
        assert a.handle_change(ca, ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert not a.handle_change(cb, ChangeSource.BROADCAST,
                                   rebroadcast=False)
        assert peer.actor_id in a._equiv_quarantined
        # held while the window is open (virtual time unmoved)
        assert not a.handle_change(peer.honest(2, "held"),
                                   ChangeSource.BROADCAST,
                                   rebroadcast=False)
        clk.jump(6.0)  # the window elapses without any wall time
        assert a.handle_change(peer.honest(3, "paroled"),
                               ChangeSource.BROADCAST,
                               rebroadcast=False)
        assert peer.actor_id not in a._equiv_quarantined
    finally:
        a.storage.close()


def test_agent_hlc_rides_injected_wall(tmp_path):
    """The HLC physical source reads the injected clock's wall — so
    HLC stamps (and therefore journal merge keys) are deterministic
    under a fixed virtual epoch."""
    from corrosion_tpu.agent.testing import make_offline_agent

    clk = VirtualClock()
    a = make_offline_agent(tmpdir=str(tmp_path), clock=clk)
    try:
        ts = a.clock.new_timestamp()
        assert abs(ts.wall_seconds() - clk.wall()) < 1e-3
        clk.jump(2.0)
        ts2 = a.clock.new_timestamp()
        assert abs(ts2.wall_seconds() - clk.wall()) < 1e-3
    finally:
        a.storage.close()


# ---------------------------------------------------------------------------
# campaign smokes: one cell per fault family at N=64, virtual time
# ---------------------------------------------------------------------------


def _vcell(tmp_path, family, **kw):
    from corrosion_tpu.sim.scenarios import virtual_scenario_cell

    kwargs = dict(
        n=64, seed=3, writes=4, heal_after=0.5, stall_ms=150.0,
        timeout=60.0, base_dir=str(tmp_path),
    )
    kwargs.update(kw)
    r = virtual_scenario_cell(family, **kwargs)
    assert r["passed"], r["gates"]
    assert r["no_divergence"]["ok"], r["no_divergence"]
    assert r["timeline"]["snapshots"] > 0
    return r


def test_vcell_clock_skew(tmp_path):
    r = _vcell(tmp_path, "clock_skew")
    assert r["detail"]["clock_skew_ns_nonzero"] > 0


def test_vcell_asym_partition(tmp_path):
    r = _vcell(tmp_path, "asym_partition")
    assert r["injected"]["partition"] > 0


def test_vcell_slow_io(tmp_path):
    r = _vcell(tmp_path, "slow_io")
    assert r["injected"]["disk"] > 0
    assert r["injected"]["stall"] == 1


def test_vcell_equivocation(tmp_path):
    r = _vcell(tmp_path, "equivocation")
    eq = r["detail"]["equivocations"]
    assert eq.get("content", 0) >= 1
    assert eq.get("span", 0) >= 1
    assert eq.get("quarantined", 0) >= 1


def test_vcell_compound(tmp_path):
    r = _vcell(tmp_path, "compound")
    assert r["injected"]["partition"] > 0


def test_vcell_restart_storm(tmp_path):
    r = _vcell(tmp_path, "restart_storm")
    assert r["gates"]["crash_schedule_ran"]
    assert r["timeline"]["event_counts"].get("crash", 0) >= 2
    assert r["timeline"]["event_counts"].get("restart", 0) >= 2


def test_vcell_hostile_sweep_8(tmp_path):
    r = _vcell(tmp_path, "hostile_sweep_8")
    assert r["detail"]["hostiles"] == 8
    assert r["detail"]["equivocations"].get("content", 0) >= 8


def test_vcell_equiv_during_heal(tmp_path):
    r = _vcell(tmp_path, "equiv_during_heal")
    assert r["injected"]["partition"] > 0
    assert r["gates"]["hostile_quarantined_everywhere"]


def test_vcell_skew_during_restart(tmp_path):
    r = _vcell(tmp_path, "skew_during_restart")
    assert r["gates"]["crash_schedule_ran"]
    assert r["gates"]["skew_applied"]


# ---------------------------------------------------------------------------
# determinism: byte-identical journals, identical end-state checksums
# ---------------------------------------------------------------------------


def _campaign(tmp_path, tag):
    """A deliberately fault-dense campaign: loss + partition heal +
    crash/restart + an equivocator, N=16."""
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.sim.vcluster import VirtualCluster
    from corrosion_tpu.types import ChangeSource

    plan = FaultPlan(
        seed=7, drop=0.05, partition_blocks=2, heal_after=1.0,
        crashes=(CrashEvent("n3", at=0.5, restart_at=1.5),),
    )
    c = VirtualCluster(
        16, seed=7, plan=plan, base_dir=str(tmp_path / tag)
    )
    try:
        c.ctrl.split()
        peer = EquivocatingPeer(seed=99, now_ns=c.clock.wall_ns)
        for a in c.agents.values():
            a.members.upsert(peer.actor_id, ("hostile", 0))
        ca, cb = peer.conflicting_pair(1)
        c.inject(list(range(16)), ca, ChangeSource.BROADCAST)
        c.inject(list(range(16)), cb, ChangeSource.BROADCAST,
                 delay=0.3)
        versions = []
        for w in range(4):
            origin = [0, 8][w % 2]
            v = c.write(
                origin,
                "INSERT INTO tests (id, text) VALUES (?, ?)",
                (100 + w, f"d-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.05)
        assert c.run_until_true(
            lambda: len(c.ctrl.crash_log) == 2 and not c._crashed
            and c.converged(versions),
            timeout=40,
        )
        c.run_for(0.5)
        return (
            c.journal_bytes(),
            c.state_checksum(),
            bytes(c.ctrl.decision_log),
            dict(c.ctrl.injected),
        )
    finally:
        c.close()


def test_virtual_campaign_is_byte_deterministic(tmp_path):
    """Two runs, same (seed, FaultPlan, campaign): byte-identical
    flight-recorder event journals, identical no-divergence state
    checksums, identical fault decision logs."""
    j1, cs1, log1, inj1 = _campaign(tmp_path, "run1")
    j2, cs2, log2, inj2 = _campaign(tmp_path, "run2")
    assert j1 == j2
    assert cs1 == cs2
    assert log1 == log2
    assert inj1 == inj2
    # the journal is substantive, not vacuously equal
    events = json.loads(j1)
    kinds = {e["kind"] for e in events}
    assert "crash" in kinds and "restart" in kinds
    assert "sync_client_end" in kinds
    assert len(events) > 20


def test_different_seed_changes_the_journal(tmp_path):
    """The negative control: a different campaign seed must NOT
    reproduce the journal (otherwise the determinism assertion is
    comparing constants)."""
    from corrosion_tpu.sim.vcluster import VirtualCluster

    def mini(seed, tag):
        c = VirtualCluster(
            8, seed=seed,
            plan=FaultPlan(seed=seed, drop=0.1),
            base_dir=str(tmp_path / tag),
        )
        try:
            v = c.write(
                0, "INSERT INTO tests (id, text) VALUES (?, ?)",
                (1, "x"),
            )
            actor = c.agents["n0"].actor_id
            assert c.run_until_true(
                lambda: c.converged([(actor, v)]), timeout=30
            )
            return c.journal_bytes(), c.state_checksum()
        finally:
            c.close()

    j1, _ = mini(1, "s1")
    j2, _ = mini(2, "s2")
    assert j1 != j2


def test_virtual_restart_resumes_identity_and_digests(tmp_path):
    """A virtual crash/restart resumes from the same node directory:
    same actor id, bumped incarnation, and the persisted equivocation
    digests re-arm the detector in the reborn node."""
    from corrosion_tpu.faults import EquivocatingPeer
    from corrosion_tpu.sim.vcluster import VirtualCluster
    from corrosion_tpu.types import ChangeSource

    plan = FaultPlan(
        seed=5, crashes=(CrashEvent("n2", at=0.3, restart_at=0.8),),
    )
    c = VirtualCluster(8, seed=5, plan=plan, base_dir=str(tmp_path))
    try:
        peer = EquivocatingPeer(seed=42, now_ns=c.clock.wall_ns)
        for a in c.agents.values():
            a.members.upsert(peer.actor_id, ("hostile", 0))
        ca, cb = peer.conflicting_pair(1)
        c.inject(list(range(8)), ca, ChangeSource.BROADCAST)
        c.run_for(0.1)
        actor_before = c.agents["n2"].actor_id
        inc_before = c.agents["n2"].incarnation
        # the whole schedule (crash AND restart) must actually run —
        # "nothing crashed" is vacuously true before the crash fires
        assert c.run_until_true(
            lambda: len(c.ctrl.crash_log) == 2 and not c._crashed,
            timeout=10,
        )
        reborn = c.agents["n2"]
        assert reborn.actor_id == actor_before
        assert reborn.incarnation == inc_before + 1
        # the reloaded digest catches the post-reboot conflicting
        # re-send immediately
        assert (peer.actor_id, 1) in reborn._equiv_digests
        assert not reborn.handle_change(cb, ChangeSource.BROADCAST,
                                        rebroadcast=False)
        assert peer.actor_id in reborn._equiv_quarantined
    finally:
        c.close()


# ---------------------------------------------------------------------------
# virtual-vs-real parity (small tier-1 cell; N=32 ships in the
# TIMELINE_N512 artifact)
# ---------------------------------------------------------------------------


def test_virtual_real_parity_small(tmp_path):
    from corrosion_tpu.sim.timeline import virtual_real_parity

    p = virtual_real_parity(
        n=10, heal_after=1.0, seed=0, base_dir=str(tmp_path)
    )
    assert p["passed"], p["gates"]


# ---------------------------------------------------------------------------
# virtual timeline trajectory vs the kernel (the N=512 gate's shape,
# smoke-scale)
# ---------------------------------------------------------------------------


def test_virtual_timeline_trajectory_gates_n64(tmp_path):
    from corrosion_tpu.sim.timeline import (
        kernel_coverage_prediction,
        trajectory_gates,
        virtual_timeline_cell,
    )

    cell = virtual_timeline_cell(
        64, heal_after=1.28, seed=0, timeout=40,
        base_dir=str(tmp_path),
    )
    assert cell["converged"]
    pred = kernel_coverage_prediction(64, 64, seeds=4)
    traj = trajectory_gates(cell, pred, 1.28)
    assert all(traj["gates"].values()), traj


# ---------------------------------------------------------------------------
# signed attribution + Byzantine sync-serve cells (docs/faults.md)
# ---------------------------------------------------------------------------


def test_vcell_framing_relay(tmp_path):
    """The headline negative control: the tampering relay is convicted
    on every victim while the framed honest origin is quarantined on
    ZERO nodes."""
    r = _vcell(tmp_path, "framing_relay")
    assert r["detail"]["framing"]["origin_quarantined_nodes"] == 0
    assert r["detail"]["framing"]["sig_fail_verifications"] > 0


def test_vcell_signed_equivocator(tmp_path):
    r = _vcell(tmp_path, "signed_equivocator")
    assert r["gates"]["signed_verdict_permanent"]
    assert r["gates"]["proof_survived_restart"]
    assert r["gates"]["zero_post_restart_rows"]


def test_vcell_byz_sync_server(tmp_path):
    r = _vcell(tmp_path, "byz_sync_server")
    rejects = r["detail"]["byz"]["client_rejects"]
    for reason in ("advertised_range", "need_cap", "frame_garbage",
                   "deadline"):
        assert rejects.get(reason, 0) >= 1, (reason, rejects)


def test_vcell_hostile_sweep_32_signed(tmp_path):
    r = _vcell(tmp_path, "hostile_sweep_32_signed")
    assert r["detail"]["hostiles"] == 32
    assert r["gates"]["signed_verdict_permanent"]


# ---------------------------------------------------------------------------
# determinism of the signed campaigns: byte-identical journals + fault
# logs across two runs, different-seed negative control
# ---------------------------------------------------------------------------


def _signed_campaign(tmp_path, tag, family, seed):
    from corrosion_tpu.sim.scenarios import (
        _virtual_framing_relay,
        _virtual_hostile_attack,
        build_virtual_plan,
    )
    from corrosion_tpu.sim.vcluster import VirtualCluster

    plan = build_virtual_plan(family, seed, 0.5, 150.0, 16)
    c = VirtualCluster(
        16, seed=seed, plan=plan, base_dir=str(tmp_path / tag),
        sign=True, sig_spot_check_rate=0.05,
    )
    try:
        if family == "framing_relay":
            _virtual_framing_relay(c, seed)
        else:
            _virtual_hostile_attack(c, seed, 1, signed=True)
        versions = []
        for w in range(3):
            origin = [0, 9][w % 2]
            v = c.write(
                origin, "INSERT INTO tests (id, text) VALUES (?, ?)",
                (600 + w, f"s-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.05)
        assert c.run_until_true(
            lambda: (not c.plan.crashes
                     or (len(c.ctrl.crash_log) == 2 and not c._crashed))
            and c.converged(versions),
            timeout=40,
        )
        c.run_for(0.5)
        return (
            c.journal_bytes(),
            c.state_checksum(),
            bytes(c.ctrl.decision_log),
        )
    finally:
        c.close()


@pytest.mark.parametrize("family", ["framing_relay", "signed_equivocator"])
def test_signed_campaigns_are_byte_deterministic(tmp_path, family):
    """Two runs of one (seed, plan, campaign): byte-identical flight
    journals, identical state checksums, identical fault decision logs
    — verification, signing, spot checks and proofs included."""
    import json as _json

    j1, cs1, log1 = _signed_campaign(tmp_path, "run1", family, seed=11)
    j2, cs2, log2 = _signed_campaign(tmp_path, "run2", family, seed=11)
    assert j1 == j2
    assert cs1 == cs2
    assert log1 == log2
    events = _json.loads(j1)
    kinds = {e["kind"] for e in events}
    # substantive journals: the verdict/quarantine seams actually fired
    assert "quarantine" in kinds
    if family == "signed_equivocator":
        assert "equivocation" in kinds
        assert "crash" in kinds and "restart" in kinds
    # the different-seed negative control
    j3, cs3, _log3 = _signed_campaign(tmp_path, "run3", family, seed=12)
    assert j3 != j1 or cs3 != cs1


# ---------------------------------------------------------------------------
# snapshot-bootstrap cells (docs/sync.md): restart storms install via
# snapshot, hostile snapshot servers are contained, mid-install deaths
# recover — plus byte-determinism over the new snap fault knobs
# ---------------------------------------------------------------------------


def test_vcell_restart_storm_snapshot(tmp_path):
    r = _vcell(tmp_path, "restart_storm_snapshot")
    assert r["gates"]["reborn_installed_via_snapshot"]
    assert r["gates"]["snapshots_served"]
    assert r["detail"]["snapshot"]["installs_ok"] >= 1
    assert r["timeline"]["event_counts"].get("snap_install", 0) >= 1
    assert r["timeline"]["event_counts"].get("snap_serve", 0) >= 1


def test_vcell_byz_snapshot_server(tmp_path):
    """Containment: every tampered serve dies on the whole-snapshot
    digest gate, NOTHING installs cluster-wide, zero tampered rows,
    and the victims still converge — change-by-change via honest
    peers (which advertise no floors in this cell)."""
    r = _vcell(tmp_path, "byz_snapshot_server")
    assert r["gates"]["rejected_snap_digest"]
    assert r["gates"]["hostile_never_installed"]
    assert r["gates"]["zero_tampered_rows"]
    assert r["detail"]["snapshot"]["snap_digest_rejects"] >= 3
    assert r["timeline"]["event_counts"].get("snap_abort", 0) >= 1


def test_vcell_crash_mid_install(tmp_path):
    """A node killed at EVERY journal stage (mid-stream, marker-
    durable, post-swap) boots into the classified recovery outcome
    and re-converges."""
    r = _vcell(tmp_path, "crash_mid_install")
    assert r["gates"]["snap_crashes_fired"]
    assert r["gates"]["recovery_retry_seen"]
    assert r["gates"]["recovery_finalized_seen"]
    assert r["gates"]["retries_installed"]


def _snap_campaign(tmp_path, tag, seed):
    """A fault-dense snapshot campaign at N=12: compacted floors,
    a wiped victim killed mid-install (``faults.SnapFault``), clean
    retry, full convergence — the determinism surface for the new
    snapshot fault knobs."""
    from corrosion_tpu.faults import CrashEvent, FaultPlan, SnapFault
    from corrosion_tpu.sim.vcluster import VirtualCluster

    victim = "n9"
    plan = FaultPlan(
        seed=seed,
        crashes=(CrashEvent(victim, at=0.1, restart_at=0.7),),
        snap_faults=(
            SnapFault(victim, "crash_installing", restart_delay=0.3),
        ),
    )
    c = VirtualCluster(
        12, seed=seed, plan=plan, base_dir=str(tmp_path / tag),
        defer_crashes=True, snapshot_retain_versions=0,
    )
    try:
        versions = []
        for w in range(6):
            origin = [0, 4][w % 2]
            v = c.write(
                origin, "INSERT INTO tests (id, text) VALUES (?, ?)",
                (700 + w, f"sn-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.04)
        assert c.run_until_true(
            lambda: c.converged(versions), timeout=30
        )
        for a in c.agents.values():
            a._compaction_pass()
        t0 = c.clock.monotonic()
        c.schedule_plan_crashes(t0)
        c.schedule_wipe(victim, t0 + 0.4)
        assert c.run_until_true(
            lambda: len(c.ctrl.crash_log) >= 4 and not c._crashed
            and c.converged(versions),
            timeout=40,
        ), (c.ctrl.crash_log, c._crashed)
        c.run_for(0.5)
        return (
            c.journal_bytes(),
            c.state_checksum(),
            bytes(c.ctrl.decision_log),
            dict(c.ctrl.injected),
        )
    finally:
        c.close()


def test_snapshot_campaign_is_byte_deterministic(tmp_path):
    """Campaign byte-determinism extends to the snapshot fault knobs:
    identical journals, state checksums, decision logs and injected
    counts across two runs; different seed diverges."""
    import json as _json

    j1, cs1, log1, inj1 = _snap_campaign(tmp_path, "run1", seed=21)
    j2, cs2, log2, inj2 = _snap_campaign(tmp_path, "run2", seed=21)
    assert j1 == j2
    assert cs1 == cs2
    assert log1 == log2
    assert inj1 == inj2
    assert inj1["snap_crash"] == 1
    kinds = {e["kind"] for e in _json.loads(j1)}
    assert {"crash", "restart", "snap_serve"} <= kinds
    j3, cs3, _log3, _inj3 = _snap_campaign(tmp_path, "run3", seed=22)
    assert j3 != j1 or cs3 != cs1
