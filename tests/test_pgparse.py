"""Recursive-descent PG parser + SQLite emitter (agent/pgparse.py).

Pins the grammar's reach (what parses and what deliberately falls
back), the AST queries the session layer relies on (table refs for
catalog routing, RETURNING names, statement class), and the emitted
SQLite SQL with $N parameter order.
"""

import pytest

from corrosion_tpu.agent.pgparse import (
    Delete,
    Insert,
    Select,
    Unsupported,
    Update,
    emit,
    parse_statement,
    returning_names,
    table_refs,
)


def _emit(sql, strip=("public",)):
    return emit(parse_statement(sql), strip_schemas=strip)


def test_roundtrip_and_param_order():
    out, order = _emit(
        "SELECT a, b AS x FROM t WHERE a > $2 AND b = $1 LIMIT $3")
    assert out == (
        "SELECT a, b AS x FROM t WHERE a > ? AND b = ? LIMIT ?"
    )
    assert order == [2, 1, 3]


def test_pg_isms_translate_inside_expressions():
    out, _ = _emit(r"SELECT x::pg_catalog.int8, E'a\nb', now() FROM t")
    assert "::" not in out and "E'" not in out
    assert "datetime('now')" in out
    out, _ = _emit("SELECT a FROM t WHERE a ILIKE $1")
    assert " LIKE " in out


def test_statement_classes():
    assert isinstance(parse_statement("SELECT 1"), Select)
    assert isinstance(
        parse_statement("WITH v AS (SELECT 1) INSERT INTO t SELECT * FROM v"),
        Insert,
    )
    assert isinstance(parse_statement("UPDATE t SET a = 1"), Update)
    assert isinstance(parse_statement("DELETE FROM t WHERE a = 1"), Delete)
    assert isinstance(parse_statement("VALUES (1), (2)"), Select)


def test_table_refs_reach_subqueries_and_ctes():
    node = parse_statement(
        "WITH c AS (SELECT * FROM cte_src) "
        "SELECT (SELECT max(x) FROM sub1), a FROM main "
        "JOIN j1 ON j1.id = main.id "
        "WHERE main.x IN (SELECT y FROM sub2) "
        "UNION SELECT b, 1 FROM other"
    )
    names = {".".join(q.parts) for q in table_refs(node)}
    assert names == {"cte_src", "sub1", "main", "j1", "sub2", "other"}
    # CTE names shadow same-named tables
    node = parse_statement("WITH t AS (SELECT 1) SELECT * FROM t")
    assert table_refs(node) == []


def test_returning_names_and_star():
    node = parse_statement(
        "INSERT INTO t (a) VALUES (1) RETURNING id, a AS alpha, b + 1")
    names = returning_names(node, lambda tbl: ["x", "y"])
    assert names[:2] == ["id", "alpha"]
    node = parse_statement("DELETE FROM t RETURNING *")
    assert returning_names(node, lambda tbl: ["c1", "c2"]) == ["c1", "c2"]
    assert returning_names(parse_statement("SELECT 1"), None) is None


def test_on_conflict_shapes():
    out, _ = _emit(
        "INSERT INTO t (a, b) VALUES ($1, $2) "
        "ON CONFLICT (a) DO UPDATE SET b = excluded.b WHERE t.c > 0")
    assert "ON CONFLICT (a) DO UPDATE SET" in out
    out, _ = _emit("INSERT INTO t (a) VALUES (1) ON CONFLICT DO NOTHING")
    assert out.endswith("ON CONFLICT DO NOTHING")


def test_locking_clause_dropped():
    out, _ = _emit("SELECT a FROM t WHERE id = $1 FOR UPDATE SKIP LOCKED")
    assert "FOR" not in out and out.endswith("?")


def test_schema_stripping():
    out, _ = _emit("SELECT a FROM public.t JOIN public.u ON t.x = u.x")
    assert "public." not in out
    out, _ = _emit(
        "SELECT relname FROM pg_catalog.pg_class",
        strip=("public", "pg_catalog", "information_schema"),
    )
    assert out == "SELECT relname FROM pg_class"


def test_unsupported_shapes_fall_back():
    for sql in (
        "SELECT DISTINCT ON (a) a, b FROM t",
        "SELECT * FROM t NATURAL JOIN u",
        "SELECT * FROM t TABLESAMPLE BERNOULLI (10)",
        "SELECT a FROM generate_series(1, 10)",
        "COPY t FROM STDIN",
        "SELECT * FROM (t JOIN u ON t.id = u.id)",
        "DECLARE c CURSOR FOR SELECT 1",
    ):
        with pytest.raises(Unsupported):
            parse_statement(sql)
    # DELETE USING parses but the emitter refuses (no sqlite form)
    node = parse_statement("DELETE FROM t USING u WHERE t.id = u.id")
    with pytest.raises(Unsupported):
        emit(node)


def test_case_and_builtin_syntax_forms():
    out, _ = _emit(
        "SELECT CASE WHEN a > 1 THEN 'big' ELSE 'small' END, "
        "count(*) FILTER (WHERE a > 0) FROM t GROUP BY b")
    assert "CASE WHEN" in out and "END" in out
    out, _ = _emit("SELECT a FROM t ORDER BY a DESC NULLS LAST")
    assert "NULLS LAST" in out  # sqlite 3.30+ accepts it natively


def test_update_from_and_compound():
    out, _ = _emit(
        "UPDATE t AS tt SET a = u.b FROM u WHERE u.id = tt.id "
        "RETURNING tt.a")
    assert out.startswith("UPDATE t AS tt SET")
    assert " FROM u WHERE" in out
    out, _ = _emit(
        "SELECT a FROM t UNION ALL SELECT b FROM u "
        "INTERSECT SELECT c FROM v ORDER BY 1 LIMIT 3")
    assert "UNION ALL" in out and "INTERSECT" in out
    assert out.endswith("ORDER BY 1 LIMIT 3")


def test_upsert_after_select_source_gets_where():
    """sqlite requires WHERE before ON CONFLICT on a SELECT source
    (parser-ambiguity rule); the emitter injects WHERE true."""
    out, _ = _emit(
        "INSERT INTO t (a) SELECT a FROM u ON CONFLICT (a) DO NOTHING")
    assert "WHERE true ON CONFLICT" in out
    out, _ = _emit(
        "INSERT INTO t (a) SELECT a FROM u WHERE a > 0 "
        "ON CONFLICT (a) DO NOTHING")
    assert "WHERE a > 0 ON CONFLICT" in out


def test_recursive_cte_self_reference_not_a_table_ref():
    """WITH RECURSIVE: the self-reference is the CTE, not a table —
    a catalog-sounding name must not leak into routing refs."""
    node = parse_statement(
        "WITH RECURSIVE pg_class(n) AS ("
        " SELECT 1 UNION ALL SELECT n + 1 FROM pg_class WHERE n < 5)"
        " SELECT * FROM pg_class")
    assert table_refs(node) == []
    # non-recursive WITH: the body's same-named ref IS the real table
    node = parse_statement(
        "WITH t AS (SELECT * FROM t) SELECT * FROM t")
    assert [q.base for q in table_refs(node)] == ["t"]
