"""Frontier-sparse exact kernel (sim/calibrate.py, N=256k-1M+).

The sparse representation — per-node capped recent-target rings plus
the origin's arithmetic ring0 tier — must be BITWISE the bitpacked
``packed_exact_tick`` at N<=256 (the parity-oracle discipline PRs 1/3-5
established: the dense kernel stays the oracle, the sparse kernel is
how the numbers are produced at scale), the equality must have
discriminating power (a seeded corruption diverges), and the frontier
set must obey the protocol's own lifecycle invariants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.sim.calibrate import (
    HeadlineExactConfig,
    frontier_exact_init,
    frontier_exact_tick,
    frontier_ring_cap,
    frontier_seed_batch,
    frontier_sent_bitmap,
    packed_exact_init,
    packed_exact_tick,
    run_exact_headline,
)

DENSE_FIELDS = ("infected", "tx", "next_send", "msgs", "pending")

#: a captured Members RTT-ring distribution shape (leading empty tiers
#: are real: nothing lives under the ring0 edge in the capture)
MEASURED_WEIGHTS = (0, 0, 2, 2, 6, 1)


def _headline_cfg(n=256, **over):
    base = dict(
        n_nodes=n, fanout=4, ring0_size=64, max_transmissions=8,
        loss=0.05, partition_blocks=2, heal_tick=3, sync_interval=2,
        backoff_ticks=0.5, max_ticks=48, chunk_ticks=8,
    )
    base.update(over)
    return HeadlineExactConfig(**base)


def _assert_lockstep(cfg, key, ticks=16):
    """Run both kernels tick-for-tick on the same keys and assert every
    dense leaf AND the ring-decoded bitmap stay bitwise equal."""
    ref = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
    fr = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))
    for t in range(ticks):
        kt = jax.random.fold_in(key, t)
        ref = packed_exact_tick(ref, kt, cfg)
        fr = frontier_exact_tick(fr, kt, cfg)
        for f in DENSE_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(fr, f)), np.asarray(getattr(ref, f)),
                err_msg=f"{f} diverged at tick {t}",
            )
        np.testing.assert_array_equal(
            frontier_sent_bitmap(fr, cfg), np.asarray(ref.sent),
            err_msg=f"sent bitmap diverged at tick {t}",
        )
    return ref, fr


def test_frontier_matches_packed_bitwise_headline_shape():
    """Full headline shape (ring0 tier, loss, partition + heal, sync,
    backoff) at N=256: the sparse kernel is bitwise the dense oracle,
    including the ring decoded back to the [N, N/8] bitmap."""
    cfg = _headline_cfg()
    ref, _ = _assert_lockstep(cfg, jax.random.PRNGKey(11), ticks=16)
    # the run exercised real spread (not vacuous equality of nothing)
    assert bool(np.asarray(ref.infected).all())


@pytest.mark.parametrize("overrides", [
    {"topology": "het_ring"},
    {"topology": "wan_two_region"},
    {"topology": "measured_ring", "rtt_tier_weights": MEASURED_WEIGHTS},
    {"topology": "wan_two_region", "wan_cross_loss": 0.0,
     "wan_latency_ticks": 2},
    {"topology": "wan_two_region", "wan_latency_ticks": 3},
], ids=["het_ring", "wan_two_region", "measured_ring", "wan_latency",
        "wan_latency_plus_loss"])
def test_frontier_matches_packed_bitwise_topologies(overrides):
    """The scenario families beyond uniform fanout keep the bit-match:
    both kernels implement them from the same arithmetic + RNG
    stream — including the measured-RTT tier map and the WAN latency
    queue (with and without cross-region loss on top)."""
    cfg = _headline_cfg(
        n=256, partition_blocks=1, heal_tick=0, **overrides,
    )
    ref, _ = _assert_lockstep(cfg, jax.random.PRNGKey(5), ticks=20)
    assert bool(np.asarray(ref.infected).any())


def test_frontier_seeded_corruption_negative_control():
    """The equality assertion has discriminating power: corrupting ONE
    ring slot (a remembered target swapped for another) must desync
    the trajectories within a few ticks — a sampler that consults the
    corrupted exclusion set draws a different tuple, and one diverging
    draw re-keys every later tick."""
    cfg = _headline_cfg(n=256, loss=0.0, partition_blocks=1, heal_tick=0,
                        backoff_ticks=0.0)
    key = jax.random.PRNGKey(3)
    ref = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
    fr = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))
    # let the epidemic spread a little so rings are non-trivial
    for t in range(4):
        kt = jax.random.fold_in(key, t)
        ref = packed_exact_tick(ref, kt, cfg)
        fr = frontier_exact_tick(fr, kt, cfg)
    # seeded corruption: the origin's first remembered target -> writer+1
    corrupt = fr.ring.at[0, 0].set(jnp.int32(1))
    assert int(corrupt[0, 0]) != int(fr.ring[0, 0])
    fr = fr._replace(ring=corrupt)
    diverged = False
    for t in range(4, 12):
        kt = jax.random.fold_in(key, t)
        ref = packed_exact_tick(ref, kt, cfg)
        fr = frontier_exact_tick(fr, kt, cfg)
        if not np.array_equal(
            frontier_sent_bitmap(fr, cfg), np.asarray(ref.sent)
        ):
            diverged = True
            break
    assert diverged, "corrupted ring produced an identical trajectory"


def test_runner_sparse_matches_dense_rank_stats():
    """``run_exact_headline(kernel=...)`` dispatch cannot move the
    published numbers: identical per-seed rank statistics from both
    representations (the committed BENCH_FRONTIER exactness gate, as a
    tier-1 test)."""
    cfg = HeadlineExactConfig(
        n_nodes=1000, fanout=4, ring0_size=64, max_transmissions=8,
        loss=0.05, sync_interval=4, max_ticks=64, chunk_ticks=8,
    )
    dense = run_exact_headline(cfg, n_seeds=3, seed=0, kernel="dense")
    sparse = run_exact_headline(cfg, n_seeds=3, seed=0, kernel="sparse")
    for k in ("converged_frac", "ticks_p50", "ticks_p99",
              "msgs_per_node_mean", "msgs_per_node_p99"):
        assert dense[k] == sparse[k], k
    assert dense["kernel"] == "dense"
    assert sparse["kernel"] == "sparse"


def test_frontier_set_invariants_under_loss():
    """The frontier lifecycle the representation is named for:

    * a node ENTERS the frontier only by infection, with a fresh
      budget;
    * it LEAVES only when its payload is fully propagated from its own
      view — budget exhausted with every send remembered (ring
      occupancy == max_transmissions * fanout);
    * once out, it never re-enters (infection is monotone and a node
      learns at most once);
    * loss re-activates the propagation wave: nodes missed by dropped
      sends are infected at strictly later ticks and bring fresh
      budget into the frontier long after the origin's wave started.
    """
    cfg = HeadlineExactConfig(
        n_nodes=512, fanout=4, ring0_size=64, max_transmissions=4,
        loss=0.3, sync_interval=6, max_ticks=64, chunk_ticks=8,
    )
    cap = frontier_ring_cap(cfg)
    key = jax.random.PRNGKey(7)
    st = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))

    def snap(s):
        return {
            "frontier": np.asarray(s.infected & (s.tx > 0)),
            "infected": np.asarray(s.infected),
            "occupancy": (np.asarray(s.ring) < cfg.n_nodes).sum(axis=1),
            "tx": np.asarray(s.tx),
        }

    prev = snap(st)
    entry_ticks = []
    exited = np.zeros(cfg.n_nodes, bool)
    for t in range(24):
        st = frontier_exact_tick(st, jax.random.fold_in(key, t), cfg)
        cur = snap(st)
        entered = cur["frontier"] & ~prev["frontier"]
        left = prev["frontier"] & ~cur["frontier"]
        # entry only via infection, with the full fresh budget
        assert (cur["infected"][entered]).all()
        assert (cur["tx"][entered] == cfg.max_transmissions).all()
        # exit only with budget exhausted AND every send remembered
        assert (cur["tx"][left] == 0).all()
        assert (cur["occupancy"][left] == cap).all()
        # no resurrection
        assert not (exited & cur["frontier"]).any()
        exited |= left
        if entered.any():
            entry_ticks.append(t)
        prev = cur
    # the wave re-activated across many distinct ticks (loss stragglers
    # infected late), not in one synchronous burst
    assert len(set(entry_ticks)) >= 4
    assert exited.any()


def test_frontier_wan_isolation_and_sync_heal():
    """wan_two_region at full cross loss: gossip alone never crosses
    (region 1 stays uninfected with sync off); anti-entropy sessions
    cross unharmed, so the same config with sync on converges."""
    base = dict(
        n_nodes=512, fanout=4, ring0_size=64, max_transmissions=8,
        loss=0.0, max_ticks=48, chunk_ticks=8,
        topology="wan_two_region", wan_cross_loss=1.0,
    )
    key = jax.random.PRNGKey(1)
    cfg = HeadlineExactConfig(**base, sync_interval=0)
    st = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))
    for t in range(16):
        st = frontier_exact_tick(st, jax.random.fold_in(key, t), cfg)
    infected = np.asarray(st.infected)
    assert infected[:256].sum() > 16
    assert infected[256:].sum() == 0
    healed = run_exact_headline(
        HeadlineExactConfig(**base, sync_interval=4), n_seeds=2, seed=0,
        kernel="sparse",
    )
    assert healed["converged_frac"] == 1.0


def test_frontier_het_ring_slows_the_tail():
    """The heterogeneous-RTT ring's slow arc drives the convergence
    tail: matched configs, strictly later convergence than uniform."""
    base = dict(
        n_nodes=1000, fanout=4, ring0_size=64, max_transmissions=8,
        loss=0.05, sync_interval=8, max_ticks=96, chunk_ticks=8,
    )
    uni = run_exact_headline(
        HeadlineExactConfig(**base), n_seeds=3, seed=0, kernel="sparse",
    )
    het = run_exact_headline(
        HeadlineExactConfig(**base, topology="het_ring", rtt_tiers=6),
        n_seeds=3, seed=0, kernel="sparse",
    )
    assert uni["converged_frac"] == het["converged_frac"] == 1.0
    assert het["ticks_p50"] > uni["ticks_p50"]


def test_frontier_seed_batch_tracks_ring_budget():
    """The sparse batching policy is governed by the O(N*cap) ring, so
    shapes the dense bitmap capped at one seed fit many."""
    from corrosion_tpu.sim.calibrate import exact_seed_batch

    big = HeadlineExactConfig(n_nodes=256_000)
    assert exact_seed_batch(big, 16, n_shards=1) == 1
    assert frontier_seed_batch(big, 16, n_shards=1) == 16
    million = HeadlineExactConfig(n_nodes=1_000_000)
    assert frontier_seed_batch(million, 32, n_shards=1) >= 16
    # explicit budget override respected
    assert frontier_seed_batch(million, 32, hbm_budget_bytes=1) == 1


def test_ring_never_overflows_at_budget_exhaustion():
    """Structural soundness of the capped ring: after a long lossless
    run every retired node's ring holds exactly cap distinct targets
    and no slot was ever overwritten (occupancy == msgs for non-origin
    broadcast-only nodes)."""
    cfg = HeadlineExactConfig(
        n_nodes=400, fanout=4, ring0_size=0, max_transmissions=4,
        loss=0.0, sync_interval=0, max_ticks=64, chunk_ticks=8,
    )
    key = jax.random.PRNGKey(9)
    st = frontier_exact_init(cfg, jax.random.fold_in(key, 2**20))
    for t in range(24):
        st = frontier_exact_tick(st, jax.random.fold_in(key, t), cfg)
    ring = np.asarray(st.ring)
    occ = (ring < cfg.n_nodes).sum(axis=1)
    msgs = np.asarray(st.msgs)
    np.testing.assert_array_equal(occ, msgs)
    retired = np.asarray(st.infected) & (np.asarray(st.tx) == 0)
    assert retired.any()
    assert (occ[retired] == frontier_ring_cap(cfg)).all()
    # every stored target is distinct within its row
    for i in np.nonzero(retired)[0][:16]:
        row = ring[i][ring[i] < cfg.n_nodes]
        assert len(set(row.tolist())) == len(row)


@pytest.mark.slow
def test_million_node_sweep_point():
    """The N=1M headline shape end-to-end on the sparse kernel (the
    BENCH_FRONTIER headline's tier-2 witness): converges with a sane
    msgs/node bound."""
    cfg = HeadlineExactConfig(
        n_nodes=1_000_000, fanout=4, ring0_size=256,
        max_transmissions=8, loss=0.05, sync_interval=8,
        max_ticks=192, chunk_ticks=8,
    )
    r = run_exact_headline(cfg, n_seeds=1, seed=0, kernel="sparse")
    assert r["converged_frac"] == 1.0
    assert r["kernel"] == "sparse"
    # broadcast budget cap (32) + sync session accounting
    assert r["msgs_per_node_mean"] < 64


# -- WAN latency queue (wan_latency_ticks) -----------------------------


def test_latency_zero_queue_is_inert():
    """The zero-latency identity: at ``wan_latency_ticks=0`` every
    queue op compiles out — the wan_two_region trajectory keeps
    ``pending`` all-sentinel, and a SEEDED pending entry is never
    promoted (the dense leaves stay bitwise the unseeded run's)."""
    from corrosion_tpu.sim.calibrate import LATENCY_NONE

    cfg = _headline_cfg(n=256, partition_blocks=1, heal_tick=0,
                        topology="wan_two_region")
    key = jax.random.PRNGKey(9)
    ref = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
    seeded = ref._replace(
        pending=ref.pending.at[200].set(jnp.int32(1))
    )
    for t in range(10):
        kt = jax.random.fold_in(key, t)
        ref = packed_exact_tick(ref, kt, cfg)
        seeded = packed_exact_tick(seeded, kt, cfg)
        for f in ("infected", "tx", "next_send", "msgs"):
            np.testing.assert_array_equal(
                np.asarray(getattr(seeded, f)),
                np.asarray(getattr(ref, f)),
                err_msg=f"{f} disturbed by a dead queue at tick {t}",
            )
    assert (np.asarray(ref.pending) == LATENCY_NONE).all()
    assert bool(np.asarray(ref.infected).any())


def test_latency_seeded_queue_negative_control():
    """Discriminating power of the queue machinery: with
    ``wan_latency_ticks>0`` the SAME seeded pending entry IS promoted —
    the in-flight arrival infects its node and re-keys every later
    draw, so the trajectory diverges from the unseeded run within a
    few ticks."""
    cfg = _headline_cfg(n=256, partition_blocks=1, heal_tick=0,
                        topology="wan_two_region", wan_cross_loss=0.0,
                        wan_latency_ticks=2)
    key = jax.random.PRNGKey(9)
    ref = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
    seeded = ref._replace(
        pending=ref.pending.at[200].set(jnp.int32(1))
    )
    diverged = False
    for t in range(12):
        kt = jax.random.fold_in(key, t)
        ref = packed_exact_tick(ref, kt, cfg)
        seeded = packed_exact_tick(seeded, kt, cfg)
        if not np.array_equal(
            np.asarray(seeded.infected), np.asarray(ref.infected)
        ):
            diverged = True
            break
    assert diverged, "a live queue entry left the trajectory untouched"
    assert bool(np.asarray(seeded.infected)[200])


def test_latency_conservation_no_message_dropped():
    """Latency delays, it never drops.  With in-region loss 0 and
    cross-region loss 0 every accepted delivery either commits now or
    enters the queue with arrival exactly ``tick + L``; queue entries
    only ever move earlier (scatter-MIN) and leave ONLY by promotion
    at their due tick (the promoted node is infected that tick); each
    sender's per-tick msgs increment is exactly ``fanout`` (nothing
    vanishes on the send side); and at convergence every node is
    infected with the queue all-sentinel."""
    from corrosion_tpu.sim.calibrate import LATENCY_NONE

    L = 2
    cfg = _headline_cfg(
        n=256, fanout=4, ring0_size=0, max_transmissions=8,
        backoff_ticks=0.0, loss=0.0, partition_blocks=1, heal_tick=0,
        sync_interval=0, topology="wan_two_region", wan_cross_loss=0.0,
        wan_latency_ticks=L, max_ticks=64,
    )
    key = jax.random.PRNGKey(4)
    st = packed_exact_init(cfg, jax.random.fold_in(key, 2**20))
    ever_queued = np.zeros(cfg.n_nodes, dtype=bool)
    for t in range(40):
        prev_pending = np.asarray(st.pending)
        prev_msgs = np.asarray(st.msgs)
        prev_infected = np.asarray(st.infected)
        st = packed_exact_tick(st, jax.random.fold_in(key, t), cfg)
        pending = np.asarray(st.pending)
        # send-side conservation: every sender emitted exactly fanout
        d_msgs = np.asarray(st.msgs) - prev_msgs
        assert set(np.unique(d_msgs).tolist()) <= {0, cfg.fanout}
        # additions arrive exactly L ticks out; entries never move later
        fresh = (prev_pending == LATENCY_NONE) & (pending != LATENCY_NONE)
        assert (pending[fresh] == t + L).all()
        kept = (prev_pending != LATENCY_NONE) & (pending != LATENCY_NONE)
        not_due = kept & (prev_pending > t)
        assert (pending[not_due] <= prev_pending[not_due]).all()
        # a due entry may be promoted and re-queued the same tick by a
        # fresh cross-region duplicate — the slot then holds t + L
        readded = kept & (prev_pending <= t)
        assert (pending[readded] == t + L).all()
        assert np.asarray(st.infected)[readded].all()
        # removals leave only by promotion at their due tick, and the
        # promoted node is infected that very tick
        gone = (prev_pending != LATENCY_NONE) & (pending == LATENCY_NONE)
        assert (prev_pending[gone] <= t).all()
        assert np.asarray(st.infected)[gone].all()
        ever_queued |= fresh
        del prev_infected
        if bool(np.asarray(st.infected).all()) and (
            pending == LATENCY_NONE
        ).all():
            break
    assert bool(np.asarray(st.infected).all()), "epidemic did not converge"
    assert (np.asarray(st.pending) == LATENCY_NONE).all()
    assert ever_queued.any(), "no cross-region delivery was ever queued"
    assert np.asarray(st.infected)[ever_queued].all()


def test_measured_tier_map_follows_weights():
    """``measured_tier_map`` partitions the id ring per the captured
    node-count weights (cumsum bounds), skipping empty tiers and
    always covering all n nodes."""
    from corrosion_tpu.models.broadcast import measured_tier_map

    tiers = np.asarray(measured_tier_map(100, (0, 0, 25, 25, 50)))
    assert tiers.shape == (100,)
    counts = {int(t): int((tiers == t).sum()) for t in np.unique(tiers)}
    assert counts == {3: 25, 4: 25, 5: 50}
    with pytest.raises(ValueError):
        measured_tier_map(100, (0, 0))


def test_host_memory_budget_reads_meminfo():
    """The host-memory budget derivation (the multi-host twin of the
    device-HBM budget): positive, halves per host, and the host-sharded
    seed batch it governs is at least 1 at the 10M headline shape."""
    from corrosion_tpu.sim.calibrate import host_memory_budget_bytes

    b1 = host_memory_budget_bytes(1)
    b2 = host_memory_budget_bytes(2)
    if b1 is None:
        pytest.skip("/proc/meminfo unavailable on this platform")
    assert b1 > 0 and b2 > 0
    assert abs(b1 - 2 * b2) <= 1024
    big = HeadlineExactConfig(n_nodes=10_000_000, chunk_ticks=8)
    assert frontier_seed_batch(big, 4, n_shards=2, host_sharded=True) >= 1
