"""Cross-node trace propagation tests (sync.rs:32-67 parity)."""

import asyncio
import logging
import re

import pytest

from corrosion_tpu.agent import tracing
from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_span_parenting_and_traceparent():
    with tracing.span("outer") as outer:
        tp = tracing.current_traceparent()
        assert tp == outer.traceparent
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    # remote re-parenting from the wire string
    with tracing.span("server", remote=outer.traceparent) as srv:
        assert srv.trace_id == outer.trace_id
        assert srv.parent_id == outer.span_id
    assert tracing.parse_traceparent("garbage") is None
    assert tracing.parse_traceparent(None) is None


def test_sync_round_shares_trace_id_across_nodes(run, caplog):
    """A sync round's client span (node B) and server span (node A) log
    the SAME trace id: the traceparent rode the SyncStart BiPayload."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            with caplog.at_level(logging.INFO, logger="corrosion_tpu.trace"):
                # sync runs on its own cadence (fast test timers); wait
                # until both span kinds have been logged
                def spans(name):
                    out = {}
                    for rec in caplog.records:
                        m = re.search(
                            rf"span {name} trace_id=(\w+)", rec.getMessage()
                        )
                        if m:
                            out.setdefault(m.group(1), 0)
                            out[m.group(1)] += 1
                    return out

                await wait_for(
                    lambda: spans("sync.client_round")
                    and spans("sync.server"),
                    timeout=20,
                )
                client_traces = spans("sync.client_round")
                server_traces = spans("sync.server")
            shared = set(client_traces) & set(server_traces)
            assert shared, (
                f"no shared trace ids: client={client_traces} "
                f"server={server_traces}"
            )
            # the shared trace is visible in the span ring too
            names = {
                (s.trace_id, s.name) for s in tracing.recent_spans(500)
            }
            tid = next(iter(shared))
            assert (tid, "sync.client_round") in names
            assert (tid, "sync.server") in names
            assert a.metrics.get_counter("corro_trace_spans_total") >= 1
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_trace_spans_admin_surface(run, tmp_path):
    """`trace spans` returns the recent-span ring over the admin UDS."""
    async def main():
        import asyncio as aio

        sock = str(tmp_path / "admin.sock")
        a = await launch_test_agent(tmpdir=str(tmp_path), admin_path=sock)
        try:
            with tracing.span("test.marker", origin="admin-surface"):
                pass
            from corrosion_tpu.agent.admin import AdminClient

            def call():
                c = AdminClient(sock)
                try:
                    return c.call("trace_spans", limit=50)
                finally:
                    c.close()

            spans = await aio.to_thread(call)
            ours = [s for s in spans if s["name"] == "test.marker"]
            assert ours and ours[-1]["attrs"]["origin"] == "admin-surface"
            assert ours[-1]["dur_ms"] is not None
        finally:
            await a.stop()

    run(main())


def test_span_file_export_shares_trace_across_nodes(run, tmp_path):
    """[telemetry.traces] path: finished spans append as OTLP-flavored
    JSON lines, and a sync round's client and server spans land there
    with the SAME trace id (the cross-node propagation, exported)."""
    import json

    async def main():
        from corrosion_tpu.agent import tracing

        out = tmp_path / "spans.jsonl"
        a = await launch_test_agent(trace_export_path=str(out))
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'traced')"]]
            )
            def exported_sync_pair():
                if not out.exists():
                    return False
                recs = [json.loads(l) for l in out.read_text().splitlines()]
                by_trace = {}
                for r in recs:
                    assert set(r) >= {"traceId", "spanId", "name",
                                      "startTimeUnixNano", "endTimeUnixNano"}
                    by_trace.setdefault(r["traceId"], set()).add(r["name"])
                return any(
                    {"sync.client_round", "sync.server"} <= names
                    for names in by_trace.values()
                )
            await wait_for(exported_sync_pair, timeout=20)
        finally:
            await b.stop()
            await a.stop()
            # stop() disables the export the configuring agent enabled
            assert tracing._sink is None

    run(main())


def test_recent_spans_trace_filter():
    """`recent_spans(trace_id=...)` filters BEFORE the limit applies, so
    one cross-node trace assembles without grepping the full dump."""
    with tracing.span("filter.root") as root:
        with tracing.span("filter.child"):
            pass
    for _ in range(5):  # unrelated traffic after ours
        with tracing.span("filter.noise"):
            pass
    got = tracing.recent_spans(2, trace_id=root.trace_id)
    assert [s.name for s in got] == ["filter.child", "filter.root"]
    assert all(s.trace_id == root.trace_id for s in got)
    assert tracing.recent_spans(0, trace_id=root.trace_id) == []


def test_record_reparents_and_rejects_junk():
    """`tracing.record` mints post-hoc spans: re-parented on a remote
    traceparent, on the current span, or as a trace root — and junk
    traceparents must NOT mint orphan traces."""
    with tracing.span("record.origin") as origin:
        tp = origin.traceparent
    s = tracing.record("record.apply", remote=tp, duration_ms=12.5, hop=1)
    assert s is not None
    assert s.trace_id == origin.trace_id
    assert s.parent_id == origin.span_id
    assert s.dur_ms == 12.5 and s.attrs["hop"] == 1
    assert s in tracing.recent_spans(10, trace_id=origin.trace_id)
    # junk off the wire: no span, no orphan trace
    assert tracing.record("record.bad", remote="garbage") is None
    # no remote: parents on the task-current span
    with tracing.span("record.outer") as outer:
        inner = tracing.record("record.inner")
    assert inner.trace_id == outer.trace_id
    assert inner.parent_id == outer.span_id


def test_bounded_export_rotates_once_then_drops(tmp_path):
    """[telemetry.traces] max_bytes: the spans file rotates ONCE to
    `path.1`, then further spans drop into the counted total — an
    append-forever export must not eat the disk."""
    out = tmp_path / "spans.jsonl"
    base_dropped = tracing.export_dropped_total()
    token = tracing.configure_export(str(out), max_bytes=1200)
    try:
        for i in range(60):
            with tracing.span("export.fill", i=i):
                pass
        assert (tmp_path / "spans.jsonl.1").exists()
        # the ACTIVE file stays bounded
        assert out.stat().st_size <= 1200
        # the rotated file holds the earlier spans
        assert (tmp_path / "spans.jsonl.1").stat().st_size <= 1200
        dropped = tracing.export_dropped_total() - base_dropped
        assert dropped > 0  # second fill has nowhere to rotate to
        # on-disk footprint never exceeds two files
        assert sorted(p.name for p in tmp_path.iterdir()) == [
            "spans.jsonl", "spans.jsonl.1"
        ]
    finally:
        tracing.disable_export_if(token)
    assert tracing._sink is None


def test_export_token_active_tracks_ownership(tmp_path):
    """A superseded export owner must stop claiming the process-wide
    drop total: only the token that opened the CURRENTLY active sink
    is active (the agent's drop-counter sync guards on this — without
    it, every past owner in an in-process cluster syncs the same delta
    and the summed family overcounts n-owners-fold)."""
    out1 = tmp_path / "a.jsonl"
    out2 = tmp_path / "b.jsonl"
    t1 = tracing.configure_export(str(out1))
    try:
        assert tracing.export_token_active(t1)
        assert not tracing.export_token_active(None)
        t2 = tracing.configure_export(str(out2))
        try:
            # reconfiguring supersedes the first owner
            assert not tracing.export_token_active(t1)
            assert tracing.export_token_active(t2)
        finally:
            tracing.disable_export_if(t2)
        assert not tracing.export_token_active(t2)
    finally:
        tracing.disable_export_if(t1)


def test_export_unbounded_when_max_bytes_zero(tmp_path):
    out = tmp_path / "spans.jsonl"
    base_dropped = tracing.export_dropped_total()
    token = tracing.configure_export(str(out), max_bytes=0)
    try:
        for i in range(40):
            with tracing.span("export.unbounded", i=i):
                pass
        assert not (tmp_path / "spans.jsonl.1").exists()
        assert tracing.export_dropped_total() == base_dropped
    finally:
        tracing.disable_export_if(token)
