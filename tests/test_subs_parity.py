"""Sharded-columnar matcher parity vs the single-drain per-sub oracle.

The columnar fast path (``submatch`` + ``SubsManager._drain_waves``)
must produce VERDICT-IDENTICAL materialized rows to the per-sub
incremental oracle (``delta()``/``refresh()``, kept verbatim) for every
change stream shape the wire can deliver: shuffled changeset order,
duplicated deliveries, superseded in-wave changes, stale deletes that
lose to newer column versions, and cross-table interleavings.
Randomized across >= 8 seeds in tier-1 (the serve-parity discipline),
plus a seeded-corruption negative control proving the comparison has
teeth.
"""

import os
import random
import time

import pytest

from corrosion_tpu.agent import submatch
from corrosion_tpu.agent.pack import pack_values
from corrosion_tpu.agent.pubsub import SubsManager
from corrosion_tpu.agent.runtime import ChangeSource
from corrosion_tpu.agent.testing import make_offline_agent
from corrosion_tpu.types import ActorId, Version
from corrosion_tpu.types.change import (
    SENTINEL_CID,
    Change,
    CrsqlDbVersion,
    CrsqlSeq,
)
from corrosion_tpu.types.changeset import Changeset, ChangeV1

ACTOR = b"\xaa" * 16

# every incremental shape the matcher plane serves: whole-table and
# pk-filtered columnar, projection subset, COUNT(*)-only, bounded
# ORDER BY + LIMIT, and a WHERE the columnar spec language rejects
# (stays on the per-sub oracle INSIDE the sharded arm — the in-arm
# fallback contract is part of what parity covers)
SUB_SQLS = (
    "SELECT * FROM tests",
    "SELECT text FROM tests",
    "SELECT id, text FROM tests WHERE id IN (1, 3, 5, 7)",
    "SELECT * FROM tests2 WHERE id IN (2, 4, 6)",
    "SELECT count(*) FROM tests",
    "SELECT id, text FROM tests ORDER BY id LIMIT 4",
    "SELECT id, text FROM tests WHERE id % 2 = 0",
)


def _mk_change(table, pk_int, cid, val, col_version, dbv, seq, cl):
    return Change(
        table=table, pk=pack_values([pk_int]), cid=cid, val=val,
        col_version=col_version, db_version=CrsqlDbVersion(dbv),
        seq=CrsqlSeq(seq), site_id=ACTOR, cl=cl,
    )


def _random_stream(rng, n_versions):
    """A foreign actor's ledger as a list of (version, changeset-maker)
    pairs; callers shuffle/duplicate the list before feeding.  Change
    shapes: upserts, sentinel deletes, superseded same-pk edits inside
    one changeset, occasional STALE deletes (older col_version than a
    prior upsert — the CRDT merge must reject them, and so must both
    matcher arms)."""
    out = []
    hi_ver = {}  # pk -> highest col_version issued (for staleness)
    for v in range(1, n_versions + 1):
        table = "tests" if rng.random() < 0.7 else "tests2"
        changes = []
        n = rng.randint(1, 3)
        for seq in range(n):
            pk = rng.randint(0, 9)
            key = (table, pk)
            roll = rng.random()
            if roll < 0.15:
                # delete; 1-in-3 of these deliberately stale
                cv = hi_ver.get(key, 1)
                if rng.random() < 0.33 and cv > 1:
                    cv = max(1, cv - rng.randint(1, 2))
                changes.append(_mk_change(
                    table, pk, SENTINEL_CID, None, cv, v, seq, cl=2
                ))
            else:
                cv = hi_ver.get(key, 0) + rng.randint(1, 2)
                hi_ver[key] = cv
                changes.append(_mk_change(
                    table, pk, "text", f"v{v}s{seq}", cv, v, seq, cl=1
                ))
        out.append((v, table, changes))
    return out


def _feed_stream(a, stream, rng, shuffle, duplicate):
    order = list(stream)
    if shuffle:
        rng.shuffle(order)
    for v, _table, changes in order:
        cs = Changeset.full(
            Version(v), changes, (0, len(changes) - 1),
            len(changes) - 1, a.clock.new_timestamp(),
        )
        reps = 2 if (duplicate and rng.random() < 0.3) else 1
        for _ in range(reps):
            a.handle_change(
                ChangeV1(actor_id=ActorId(ACTOR), changeset=cs),
                ChangeSource.SYNC, rebroadcast=False,
            )


def _wait_idle(mgr, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if mgr.idle():
            return
        time.sleep(0.02)
    raise TimeoutError("subs manager did not drain")


def _sub_state(handle):
    """Comparable materialization: sorted multiset of row cells."""
    with handle._lock:
        return sorted(
            (tuple(c) for _rid, c in handle.rows.values()),
            key=repr,
        )


def _ground_truth(a, sql):
    _, rows = a.storage.read_query(sql)
    return sorted((tuple(r) for r in rows), key=repr)


def _run_arm(tmpdir, stream, rng_seed, shuffle, duplicate, **cfg):
    os.makedirs(tmpdir, exist_ok=True)
    a = make_offline_agent(tmpdir, **cfg)
    mgr = SubsManager(a, tmpdir + "/subs")
    try:
        handles = [mgr.subscribe(sql) for sql in SUB_SQLS]
        _feed_stream(
            a, stream, random.Random(rng_seed + 1), shuffle, duplicate
        )
        _wait_idle(mgr)
        states = [_sub_state(h) for h in handles]
        truths = [_ground_truth(a, sql) for sql in SUB_SQLS]
        verdicts = float(
            a.metrics.get_counter_sum("corro_subs_columnar_verdicts_total")
        )
        return states, truths, verdicts
    finally:
        mgr.close()
        a.storage.close()


@pytest.mark.parametrize("seed", range(8))
def test_sharded_columnar_matcher_parity(seed, tmp_path):
    rng = random.Random(9000 + seed)
    stream = _random_stream(rng, n_versions=30)
    shuffle = seed % 2 == 1
    duplicate = seed % 4 >= 2

    col_states, col_truths, col_verdicts = _run_arm(
        str(tmp_path / "col"), stream, 9000 + seed, shuffle, duplicate,
        subs_shards=3, subs_columnar=True,
    )
    ora_states, ora_truths, _ = _run_arm(
        str(tmp_path / "ora"), stream, 9000 + seed, shuffle, duplicate,
        subs_shards=1, subs_columnar=False,
    )

    # both arms converged to the same database state...
    assert col_truths == ora_truths
    for sql, col, ora, truth in zip(
        SUB_SQLS, col_states, ora_states, col_truths
    ):
        # ...and every subscription materialized exactly the oracle's
        # rows, which are exactly the query's rows over that state
        assert col == ora, f"arm divergence for {sql!r}"
        assert col == truth, f"materialization drift for {sql!r}"
    # the sharded arm must actually have exercised the columnar path —
    # a silently-degraded fast path would make this suite vacuous
    assert col_verdicts > 0


def test_seeded_corruption_is_detected(tmp_path, monkeypatch):
    """Negative control: corrupt ONE columnar verdict and the parity
    comparison above must trip — proving it can fail."""
    rng = random.Random(77)
    stream = _random_stream(rng, n_versions=30)

    real_match_wave = submatch.match_wave
    corrupted = {"n": 0}

    def corrupt_match_wave(index, table, pks, fetch):
        # corrupt EVERY live verdict (one early corruption could be
        # healed by a later wave on the same pk before the final
        # comparison — the control must survive to the end)
        verdicts, n_pairs = real_match_wave(index, table, pks, fetch)
        for _sub_id, per in verdicts.items():
            for pk, row in per.items():
                if row is not None:
                    per[pk] = tuple(
                        "corrupt" if isinstance(c, str) else c
                        for c in row
                    )
                    corrupted["n"] += 1
        return verdicts, n_pairs

    monkeypatch.setattr(submatch, "match_wave", corrupt_match_wave)
    col_states, col_truths, _ = _run_arm(
        str(tmp_path / "col"), stream, 77, False, False,
        subs_shards=3, subs_columnar=True,
    )
    assert corrupted["n"] > 0, "control never injected its corruption"
    assert any(
        s != t for s, t in zip(col_states, col_truths)
    ), "corrupted verdict went undetected — parity check is vacuous"
