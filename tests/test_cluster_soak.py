"""Cluster soak: writes under churn, failure detection, restart catch-up.

The reference exercises this shape with ``configurable_stress_test``
(``corro-agent/src/agent/tests.rs``): many real agents, concurrent
writes, nodes dying and returning, convergence asserted at the end.
"""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_soak_writes_churn_and_restart_catchup(run, tmp_path):
    async def main():
        n = 6
        agents = []
        dirs = []
        for i in range(n):
            d = tmp_path / f"n{i}"
            d.mkdir()
            dirs.append(str(d))
            boots = (
                [f"{agents[0].gossip_addr[0]}:{agents[0].gossip_addr[1]}"]
                if agents else []
            )
            agents.append(
                await launch_test_agent(tmpdir=str(d), bootstrap=boots)
            )
        try:
            await wait_for(
                lambda: all(len(a.members.alive()) == n - 1 for a in agents),
                timeout=30,
            )

            # concurrent writes spread over several writers
            for i in range(30):
                agents[i % n].execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [i, f"w{i}"]]
                ])

            def table(a):
                return a.storage.read_query(
                    "SELECT id, text FROM tests ORDER BY id")[1]

            def all_converged(group, want):
                ref = table(group[0])
                if len(ref) != want:
                    return False
                return all(table(a) == ref for a in group[1:])

            await wait_for(lambda: all_converged(agents, 30), timeout=30)

            # kill one node; the rest must mark it down and keep going
            victim_dir = dirs[-1]
            victim_actor = agents[-1].actor_id
            await agents[-1].stop(graceful=False)  # crash: exercise suspicion
            survivors = agents[:-1]

            def victim_down_everywhere():
                from corrosion_tpu.agent.members import MemberState
                for a in survivors:
                    m = next(
                        (m for m in a.members.all()
                         if m.actor_id == victim_actor), None
                    )
                    # require the full suspicion pipeline: SUSPECT alone
                    # is not failure detection
                    if m is not None and m.state is not MemberState.DOWN:
                        return False
                return True

            await wait_for(victim_down_everywhere, timeout=30)

            # writes continue while the victim is gone
            for i in range(30, 45):
                survivors[i % (n - 1)].execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [i, f"w{i}"]]
                ])
            await wait_for(
                lambda: all(len(table(a)) == 45 for a in survivors),
                timeout=30,
            )

            # the victim restarts from its own disk state (resume, not
            # re-seed) and catches up on everything it missed via sync
            reborn = await launch_test_agent(
                tmpdir=victim_dir,
                bootstrap=[
                    f"{survivors[0].gossip_addr[0]}:"
                    f"{survivors[0].gossip_addr[1]}"
                ],
            )
            agents[-1] = reborn
            assert reborn.actor_id == victim_actor  # same identity
            await wait_for(
                lambda: len(table(reborn)) == 45
                and table(reborn) == table(survivors[0]),
                timeout=45,
            )
        finally:
            for a in agents:
                try:
                    await a.stop()
                except Exception:
                    pass

    run(main())


def test_partition_heals_via_sync(run, tmp_path):
    """A live 4-node cluster split 2|2: writes land on both sides of the
    partition, cross-partition traffic is dropped at the transport, and
    after the heal both sides converge to the union (the sim's
    partition_blocks/heal_tick scenario, on real agents)."""
    async def main():
        n = 4
        agents = []
        for i in range(n):
            d = tmp_path / f"p{i}"
            d.mkdir()
            boots = (
                [f"{agents[0].gossip_addr[0]}:{agents[0].gossip_addr[1]}"]
                if agents else []
            )
            agents.append(
                # suspicion stays OFF during the split: the test pins
                # the DATA paths (broadcast drop + sync heal), not SWIM
                # down-marking, and DOWN members would be excluded from
                # sync target selection after the heal
                await launch_test_agent(
                    tmpdir=str(d), bootstrap=boots, suspect_timeout=30.0
                )
            )
        try:
            await wait_for(
                lambda: all(len(a.members.alive()) == n - 1 for a in agents),
                timeout=30,
            )
            group = {tuple(a.gossip_addr): (i < n // 2)
                     for i, a in enumerate(agents)}

            # drop every cross-group message at each agent's transport
            originals = []

            def partition(a, side):
                t = a.transport
                send_uni, open_bi, send_udp = (
                    t.send_uni, t.open_bi, a._send_udp
                )
                originals.append((t, a, send_uni, open_bi, send_udp))

                async def blocked_uni(addr, frames, header):
                    if group.get(tuple(addr), side) != side:
                        return False  # dropped on the floor
                    return await send_uni(addr, frames, header)

                async def blocked_bi(addr):
                    if group.get(tuple(addr), side) != side:
                        raise OSError("partitioned")
                    return await open_bi(addr)

                def blocked_udp(addr, msg):
                    if group.get(tuple(addr), side) != side:
                        return
                    send_udp(addr, msg)

                t.send_uni, t.open_bi = blocked_uni, blocked_bi
                a._send_udp = blocked_udp

            for i, a in enumerate(agents):
                partition(a, i < n // 2)
            # a partition severs ESTABLISHED connections too, not just
            # new dials: drop cached cross-group muxes (live sessions
            # die with a reset) and drain the one-tick window in which
            # an open_bi that entered the ORIGINAL method before the
            # patch could still hand back a live cross-group session —
            # its handshake must complete (empty: nothing written yet)
            # before the writes land, or it may legally serve them
            # across the "partition" (the faults.FaultController
            # split() semantics, corrosion_tpu/faults.py)
            for a in agents:
                side = group[tuple(a.gossip_addr)]
                for b in agents:
                    if a is not b and group[tuple(b.gossip_addr)] != side:
                        a.transport.drop(tuple(b.gossip_addr))
            await asyncio.sleep(0.1)

            # writes on BOTH sides while split
            agents[0].execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'left')"]]
            )
            agents[2].execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'right')"]]
            )

            def table(a):
                return a.storage.read_query(
                    "SELECT id, text FROM tests ORDER BY id")[1]

            # each side sees only its own write
            await wait_for(
                lambda: table(agents[1]) == [(1, "left")]
                and table(agents[3]) == [(2, "right")],
                timeout=20,
            )
            assert table(agents[0]) == [(1, "left")]
            assert table(agents[2]) == [(2, "right")]

            # outlive the broadcast retransmission tail (send_count-
            # scaled backoff sums to ~0.75s at harness timers) so the
            # heal below can only converge through anti-entropy SYNC,
            # not leftover rebroadcasts
            await asyncio.sleep(2.0)
            assert table(agents[1]) == [(1, "left")]
            assert table(agents[3]) == [(2, "right")]

            # heal: restore the real transports
            for t, a, send_uni, open_bi, send_udp in originals:
                t.send_uni, t.open_bi = send_uni, open_bi
                a._send_udp = send_udp

            # anti-entropy merges the two histories on every node
            want = [(1, "left"), (2, "right")]
            await wait_for(
                lambda: all(table(a) == want for a in agents), timeout=45
            )
        finally:
            for a in agents:
                await a.stop()

    run(main())
