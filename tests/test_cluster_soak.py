"""Cluster soak: writes under churn, failure detection, restart catch-up.

The reference exercises this shape with ``configurable_stress_test``
(``corro-agent/src/agent/tests.rs``): many real agents, concurrent
writes, nodes dying and returning, convergence asserted at the end.
"""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_soak_writes_churn_and_restart_catchup(run, tmp_path):
    async def main():
        n = 6
        agents = []
        dirs = []
        for i in range(n):
            d = tmp_path / f"n{i}"
            d.mkdir()
            dirs.append(str(d))
            boots = (
                [f"{agents[0].gossip_addr[0]}:{agents[0].gossip_addr[1]}"]
                if agents else []
            )
            agents.append(
                await launch_test_agent(tmpdir=str(d), bootstrap=boots)
            )
        try:
            await wait_for(
                lambda: all(len(a.members.alive()) == n - 1 for a in agents),
                timeout=30,
            )

            # concurrent writes spread over several writers
            for i in range(30):
                agents[i % n].execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [i, f"w{i}"]]
                ])

            def table(a):
                return a.storage.read_query(
                    "SELECT id, text FROM tests ORDER BY id")[1]

            def all_converged(group, want):
                ref = table(group[0])
                if len(ref) != want:
                    return False
                return all(table(a) == ref for a in group[1:])

            await wait_for(lambda: all_converged(agents, 30), timeout=30)

            # kill one node; the rest must mark it down and keep going
            victim_dir = dirs[-1]
            victim_actor = agents[-1].actor_id
            await agents[-1].stop()
            survivors = agents[:-1]

            def victim_down_everywhere():
                from corrosion_tpu.agent.members import MemberState
                for a in survivors:
                    m = next(
                        (m for m in a.members.all()
                         if m.actor_id == victim_actor), None
                    )
                    # require the full suspicion pipeline: SUSPECT alone
                    # is not failure detection
                    if m is not None and m.state is not MemberState.DOWN:
                        return False
                return True

            await wait_for(victim_down_everywhere, timeout=30)

            # writes continue while the victim is gone
            for i in range(30, 45):
                survivors[i % (n - 1)].execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [i, f"w{i}"]]
                ])
            await wait_for(
                lambda: all(len(table(a)) == 45 for a in survivors),
                timeout=30,
            )

            # the victim restarts from its own disk state (resume, not
            # re-seed) and catches up on everything it missed via sync
            reborn = await launch_test_agent(
                tmpdir=victim_dir,
                bootstrap=[
                    f"{survivors[0].gossip_addr[0]}:"
                    f"{survivors[0].gossip_addr[1]}"
                ],
            )
            agents[-1] = reborn
            assert reborn.actor_id == victim_actor  # same identity
            await wait_for(
                lambda: len(table(reborn)) == 45
                and table(reborn) == table(survivors[0]),
                timeout=45,
            )
        finally:
            for a in agents:
                try:
                    await a.stop()
                except Exception:
                    pass

    run(main())
