"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip sharding is validated on virtual CPU devices (no multi-chip TPU
hardware in CI).  The provisioning logic lives in
``__graft_entry__._force_virtual_cpu`` (shared with the driver's multichip
dry run): this environment's sitecustomize registers the `axon` TPU-tunnel
PJRT plugin at interpreter start and pins ``jax_platforms``, so plain env
vars are not enough — the config must be overridden directly before the
first backend use.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_virtual_cpu  # noqa: E402

_force_virtual_cpu(8)

import jax  # noqa: E402

assert jax.default_backend() == "cpu", (
    "tests must run on the virtual CPU mesh, got " + jax.default_backend()
)
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.device_count()} "
    "(XLA_FLAGS set too late?)"
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soaks (e.g. the N=32 chaos run) excluded from tier-1",
    )
