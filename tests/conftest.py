"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip sharding is validated on virtual CPU devices (no multi-chip TPU
hardware in CI).  Note: this environment's sitecustomize registers the
`axon` TPU-tunnel PJRT plugin at interpreter start and pins
``jax_platforms``; plain env vars are not enough, so we override the config
directly before the first backend use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", (
    "tests must run on the virtual CPU mesh, got " + jax.default_backend()
)
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.device_count()} "
    "(XLA_FLAGS set too late?)"
)
