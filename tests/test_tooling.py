"""Client library, admin protocol, CLI, templates, consul sync,
devcluster, backup/restore."""

import asyncio
import json
import os
import tempfile

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from corrosion_tpu.client import ClientError, CorrosionApiClient


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_client_roundtrip(run):
    async def main():
        a = await launch_test_agent()
        try:
            client = CorrosionApiClient(a.api_addr)
            out = client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "x"]]]
            )
            assert out["version"] == 1
            cols, rows = client.query("SELECT id, text FROM tests")
            assert cols == ["id", "text"] and rows == [[1, "x"]]
            stats = client.table_stats()
            assert stats["tables"]["tests"]["row_count"] == 1
            with pytest.raises(ClientError) as e:
                client.query("SELECT nope FROM tests")
            assert e.value.status == 500
        finally:
            await a.stop()

    run(main())


def test_client_subscription_stream(run):
    async def main():
        a = await launch_test_agent()
        try:
            client = CorrosionApiClient(a.api_addr)
            stream = client.subscribe("SELECT id FROM tests")
            it = iter(stream)
            assert "columns" in next(it)
            assert "eoq" in next(it)
            client.execute([["INSERT INTO tests (id) VALUES (3)"]])
            ev = await asyncio.to_thread(next, it)
            assert ev["change"][0] == "insert"
            assert stream.last_change_id == ev["change"][3]
            # re-attach from the observed change id
            stream2 = client.subscription(
                stream.id, from_change_id=stream.last_change_id
            )
            client.execute([["INSERT INTO tests (id) VALUES (4)"]])
            it2 = iter(stream2)
            ev2 = await asyncio.to_thread(next, it2)
            assert ev2["change"][0] == "insert" and ev2["change"][2] == [4]
        finally:
            await a.stop()

    run(main())


def test_admin_protocol(run):
    async def main():
        d = tempfile.mkdtemp()
        sock = os.path.join(d, "admin.sock")
        a = await launch_test_agent(tmpdir=d, admin_path=sock)
        try:
            from corrosion_tpu.agent.admin import AdminClient

            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
            # the sync client would block the loop thread the admin server
            # runs on; call it from a worker thread like a real CLI process
            def call(cmd, **kw):
                admin = AdminClient(sock)
                try:
                    return admin.call(cmd, **kw)
                finally:
                    admin.close()

            assert await asyncio.to_thread(call, "ping") == "pong"
            st = await asyncio.to_thread(call, "sync_generate")
            assert st["heads"]  # our own head present
            ver = await asyncio.to_thread(call, "actor_version")
            assert ver["last"] == 1
            assert await asyncio.to_thread(call, "subs_list") == []
            assert await asyncio.to_thread(call, "locks") == []
            info = await asyncio.to_thread(call, "db_info")
            assert info["db_version"] == 1
            with pytest.raises(RuntimeError):
                await asyncio.to_thread(call, "bogus")
        finally:
            await a.stop()

    run(main())


def test_template_render_and_reactive_loop(run):
    async def main():
        import threading

        from corrosion_tpu.tpl import Template, render_loop, Row

        a = await launch_test_agent()
        try:
            client = CorrosionApiClient(a.api_addr)
            client.execute(
                [["INSERT INTO tests (id, text) VALUES (1, 'one')"],
                 ["INSERT INTO tests (id, text) VALUES (2, 'two')"]]
            )
            d = tempfile.mkdtemp()
            tpl_path = os.path.join(d, "t.tpl")
            out_path = os.path.join(d, "out.conf")
            with open(tpl_path, "w") as f:
                f.write(
                    "# generated\n"
                    "{% for r in sql(\"SELECT id, text FROM tests ORDER BY id\") %}"
                    "server {{ r.id }} = {{ r.text }}\n"
                    "{% endfor %}"
                    "{% if len(sql(\"SELECT id FROM tests\")) > 1 %}multi{% else %}single{% endif %}\n"
                )
            # template needs len(): provide via expression namespace
            tpl = Template(open(tpl_path).read())

            def sql(q):
                cols, rows = client.query(q)
                return [Row(cols, r) for r in rows]

            out, queries = tpl.render(sql, extra={"len": len})
            assert "server 1 = one" in out and "server 2 = two" in out
            assert out.strip().endswith("multi")
            assert len(queries) == 2

            # reactive loop: a write re-renders the file
            stop = threading.Event()
            renders = []
            t = threading.Thread(
                target=render_loop,
                args=(a.api_addr, tpl_path, out_path),
                kwargs={"stop": stop, "on_render": renders.append},
                daemon=True,
            )
            # patch len into loop renders via default ns? keep template simple:
            with open(tpl_path, "w") as f:
                f.write(
                    "{% for r in sql(\"SELECT id, text FROM tests ORDER BY id\") %}"
                    "server {{ r.id }} = {{ r.text }}\n"
                    "{% endfor %}"
                )
            t.start()
            # generous timeouts: under a loaded full-suite run the
            # subscription round-trip can take several seconds
            await wait_for(lambda: os.path.exists(out_path), timeout=30.0)
            client.execute([["INSERT INTO tests (id, text) VALUES (3, 'three')"]])
            await wait_for(
                lambda: os.path.exists(out_path)
                and "server 3 = three" in open(out_path).read(),
                timeout=30.0,
            )
            stop.set()
        finally:
            await a.stop()

    run(main())


def test_consul_sync_diffing(run):
    async def main():
        from corrosion_tpu.consul import CONSUL_SCHEMA, sync_once

        a = await launch_test_agent()
        try:
            client = CorrosionApiClient(a.api_addr)
            client.migrate(CONSUL_SCHEMA)
            state = {}
            services = {
                "web": {"Service": "web", "Port": 80, "Tags": ["a"]},
                "db": {"Service": "db", "Port": 5432},
            }
            checks = {"web-check": {"ServiceID": "web", "Status": "passing"}}
            up, dl = sync_once(client, "node1", services, checks, state)
            assert (up, dl) == (3, 0)
            _, rows = client.query("SELECT id FROM consul_services ORDER BY id")
            assert rows == [["db"], ["web"]]

            # unchanged: no writes
            up, dl = sync_once(client, "node1", services, checks, state)
            assert (up, dl) == (0, 0)

            # change one, remove one
            services["web"]["Port"] = 8080
            del services["db"]
            up, dl = sync_once(client, "node1", services, checks, state)
            assert (up, dl) == (1, 1)
            _, rows = client.query(
                "SELECT id, port FROM consul_services ORDER BY id"
            )
            assert rows == [["web", 8080]]
        finally:
            await a.stop()

    run(main())


def test_devcluster_topology_and_inprocess(run):
    from corrosion_tpu.devcluster import Topology, run_inprocess

    topo = Topology.parse("A -> B\nA -> C\n# comment\nB -> C\n")
    assert topo.nodes == ["A", "B", "C"]
    assert topo.bootstraps_for("C") == ["A", "B"]

    async def main():
        agents = await run_inprocess(topo)
        try:
            await wait_for(
                lambda: all(len(a.members.alive()) == 2 for a in agents.values())
            )
            agents["A"].execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'topo')"]]
            )
            await wait_for(
                lambda: all(
                    a.storage.conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
                    == 1
                    for a in agents.values()
                )
            )
        finally:
            for a in agents.values():
                await a.stop()

    run(main())


def test_backup_restore(run):
    async def main():
        from corrosion_tpu.agent.backup import backup, restore
        from corrosion_tpu.agent.storage import CrConn

        d = tempfile.mkdtemp()
        a = await launch_test_agent(tmpdir=d)
        db_path = a.config.db_path
        a.execute_transaction(
            [["INSERT INTO tests (id, text) VALUES (1, 'keep me')"]]
        )
        await a.stop()

        bak = os.path.join(d, "backup.db")
        backup(db_path, bak)

        # restore into a brand-new node dir
        d2 = tempfile.mkdtemp()
        new_db = os.path.join(d2, "corrosion.db")
        restore(bak, new_db)
        c = CrConn(new_db)
        assert c.conn.execute("SELECT text FROM tests WHERE id=1").fetchone() == (
            "keep me",
        )
        # scrubbed member state
        assert c.conn.execute("SELECT COUNT(*) FROM __corro_members").fetchone()[0] == 0
        c.close()

    run(main())


def test_cli_offline_commands(tmp_path):
    from corrosion_tpu.cli import build_parser

    p = build_parser()
    args = p.parse_args(["backup", "x.db", "y.db"])
    assert args.fn.__name__ == "cmd_backup"
    args = p.parse_args(["query", "SELECT 1", "--columns"])
    assert args.sql == "SELECT 1"
    args = p.parse_args(["subs", "list"])
    assert callable(args.fn)
    args = p.parse_args(["consul", "sync", "--once"])
    assert args.once


def test_metrics_endpoint_and_backoff(run):
    from corrosion_tpu.utils.backoff import Backoff

    # backoff: decorrelated jitter within [base, cap], respects max_retries
    delays = list(Backoff(base=0.1, cap=2.0, max_retries=20))
    assert len(delays) == 20
    assert all(0.1 <= d <= 2.0 for d in delays)

    async def main():
        import urllib.request

        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction([["INSERT INTO tests (id) VALUES (1)"]])
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT COUNT(*) FROM tests"
                ).fetchone()[0] == 1
            )
            url = f"http://{b.api_addr[0]}:{b.api_addr[1]}/metrics"
            with urllib.request.urlopen(url, timeout=5) as r:
                text = r.read().decode()
            assert "corro_changes_received_total" in text
            assert 'corro_table_rows{table="tests"} 1.0' in text
            assert "corro_members_alive 1.0" in text
            # per-kind gossip counters + endpoint-labeled HTTP counters
            assert 'corro_gossip_datagrams_received_total{kind="' in text
            assert "corro_gossip_datagrams_sent_total" in text
            assert 'corro_http_requests_total{endpoint="/metrics"}' in text
            # strict-exposition well-formedness: every TYPE line and
            # series unique (promtool/Prometheus reject duplicates)
            seen_types, seen_series = set(), set()
            for ln in text.splitlines():
                if ln.startswith("# TYPE"):
                    name = ln.split()[2]
                    assert name not in seen_types, f"dup TYPE {name}"
                    seen_types.add(name)
                elif ln and not ln.startswith("#"):
                    series = ln.rsplit(" ", 1)[0]
                    assert series not in seen_series, f"dup {series}"
                    seen_series.add(series)
            # round-4 breadth (collect_metrics parity, docs/telemetry.md)
            assert "corro_db_size_bytes" in text
            assert "corro_db_wal_size_bytes" in text
            assert "corro_db_freelist_pages" in text
            assert "corro_change_queue_depth" in text
            assert "corro_bcast_queue_depth" in text
            assert "corro_subs_pending_depth" in text
            assert "corro_transport_peers" in text
            assert "corro_transport_bytes_sent" in text
            # A sent the change to B over a cached uni conn, so A's
            # aggregate ConnStats are nonzero
            url_a = f"http://{a.api_addr[0]}:{a.api_addr[1]}/metrics"
            with urllib.request.urlopen(url_a, timeout=5) as r:
                text_a = r.read().decode()
            for ln in text_a.splitlines():
                if ln.startswith("corro_transport_connects"):
                    assert float(ln.split()[-1]) >= 1.0
                if ln.startswith("corro_transport_bytes_sent"):
                    assert float(ln.split()[-1]) > 0.0
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_db_lock_excludes_other_processes(tmp_path):
    """db lock's POSIX byte locks land on the offsets SQLite's unix VFS
    uses, so a live sqlite3 connection in ANOTHER process is genuinely
    excluded while the lock is held and works again after release."""
    import sqlite3
    import subprocess
    import sys

    from corrosion_tpu.agent.dblock import lock_all

    db = str(tmp_path / "locked.db")
    conn = sqlite3.connect(db)
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("CREATE TABLE t (x INTEGER)")
    conn.commit()
    conn.close()

    probe = (
        "import sqlite3, sys\n"
        f"c = sqlite3.connect({db!r}, timeout=0.2)\n"
        "try:\n"
        "    c.execute('INSERT INTO t VALUES (1)'); c.commit()\n"
        "    print('WROTE')\n"
        "except sqlite3.OperationalError as e:\n"
        "    print('BLOCKED', e)\n"
    )

    with lock_all(db, timeout_s=5):
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            timeout=30,
        )
        assert "BLOCKED" in out.stdout, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=30,
    )
    assert "WROTE" in out.stdout, out.stdout + out.stderr


def test_db_lock_cli_runs_command_under_lock(tmp_path):
    import sqlite3
    import subprocess
    import sys

    db = str(tmp_path / "locked2.db")
    sqlite3.connect(db).execute("CREATE TABLE t (x)").connection.commit()

    out = subprocess.run(
        [sys.executable, "-m", "corrosion_tpu.cli", "db", "lock", db,
         f"cp {db} {db}.copy"],
        capture_output=True, text=True, timeout=30, cwd="/root/repo",
    )
    assert out.returncode == 0, out.stderr
    import os
    assert os.path.exists(f"{db}.copy")


def test_named_param_statements(run):
    """Statement::WithNamedParams parity: [sql, {name: value}] works for
    writes and reads over the HTTP API (and ? params stay positional)."""
    async def main():
        a = await launch_test_agent()
        try:
            client = CorrosionApiClient(a.api_addr)
            out = client.execute([
                ["INSERT INTO tests (id, text) VALUES (:id, :text)",
                 {"id": 7, "text": "named"}],
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [8, "pos"]],
            ])
            assert [r["rows_affected"] for r in out["results"]] == [1, 1]
            cols, rows = client.query(
                ["SELECT text FROM tests WHERE id = :id", {"id": 7}]
            )
            assert rows == [["named"]]
        finally:
            await a.stop()

    run(main())


def test_pooled_client_failover_and_reresolve(run):
    """PooledApiClient (corro-client's DNS-pooled client): a dead
    address is marked bad and the next one serves; exhausting every
    address forces a re-resolve."""
    async def main():
        a = await launch_test_agent()
        try:
            from corrosion_tpu.client import PooledApiClient

            live = a.api_addr
            resolutions = []

            def resolver(host):
                # first resolution: a dead addr sorted before the live
                # one; later resolutions: only the live addr
                resolutions.append(host)
                if len(resolutions) == 1:
                    return ["127.1.2.3", live[0]]
                return [live[0]]

            pc = PooledApiClient("cluster.test", live[1], timeout=2.0,
                                 ttl=3600.0, resolver=resolver)

            def do_query():
                return pc.query("SELECT count(*) FROM tests")

            cols, rows = await asyncio.to_thread(do_query)
            assert rows == [[0]]
            assert resolutions == ["cluster.test"]  # one resolve so far
            # the dead address is remembered as bad: the next call goes
            # straight to the live node (no retry loop)
            _, rows = await asyncio.to_thread(do_query)
            assert rows == [[0]]

            # every address bad -> re-resolve
            pc._bad = set(pc._addrs)
            _, rows = await asyncio.to_thread(do_query)
            assert rows == [[0]]
            assert len(resolutions) == 2
        finally:
            await a.stop()

    run(main())


def test_pooled_client_execute_never_retries(run):
    """execute() is not idempotent: a connection-level failure marks the
    address bad and rotates, but the error surfaces to the caller — a
    timeout can fire after the server already applied the transaction
    (corro-client handle_error parity; ADVICE r3)."""
    async def main():
        a = await launch_test_agent()
        try:
            from corrosion_tpu.client import PooledApiClient

            live = a.api_addr

            pc = PooledApiClient("cluster.test", live[1], timeout=2.0,
                                 ttl=3600.0,
                                 resolver=lambda h: ["127.1.2.3", live[0]])

            def do_exec():
                return pc.execute(
                    ["INSERT INTO tests (id, text) VALUES (1, 'x')"])

            with pytest.raises(ClientError) as ei:
                await asyncio.to_thread(do_exec)
            assert ei.value.status == 0  # connection-level, not HTTP
            # the dead address was marked bad; the caller's own retry
            # lands on the live node and applies exactly once
            res = await asyncio.to_thread(do_exec)
            assert "results" in res
            _, rows = await asyncio.to_thread(
                lambda: pc.query("SELECT count(*) FROM tests"))
            assert rows == [[1]]
        finally:
            await a.stop()

    run(main())


def test_config_api_pg_addr_enables_pg(tmp_path):
    """[api.pg] addr in the TOML config wires up the PostgreSQL
    listener (config.rs PgConfig parity)."""
    from corrosion_tpu.agent.config import load_config

    cfg = tmp_path / "c.toml"
    cfg.write_text(
        '[db]\npath = "x.db"\n'
        '[api]\naddr = "127.0.0.1:0"\n'
        '[api.pg]\naddr = "127.0.0.1:6543"\n'
    )
    c = load_config(str(cfg))
    assert c.pg_port == 6543
    # absent section leaves PG off
    cfg2 = tmp_path / "c2.toml"
    cfg2.write_text('[db]\npath = "x.db"\n')
    assert load_config(str(cfg2)).pg_port is None


def test_devcluster_process_runtime(tmp_path):
    """The process runtime (corro-devcluster parity): parse a topology,
    spawn real agent subprocesses with generated configs, converge a
    write across them, and tear down cleanly on SIGTERM."""
    import signal as _signal
    import subprocess
    import sys
    import time

    from corrosion_tpu.client import CorrosionApiClient

    topo = tmp_path / "topo.txt"
    topo.write_text("a -> b\n")
    schema = tmp_path / "schema.sql"
    schema.write_text(
        "CREATE TABLE IF NOT EXISTS tests ("
        " id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT NOT NULL DEFAULT '');"
    )
    import random as _random
    port_base = _random.randrange(30000, 60000, 16)
    proc = subprocess.Popen(
        [sys.executable, "-m", "corrosion_tpu.devcluster", str(topo),
         "--schema", str(schema), "--base-dir", str(tmp_path / "cluster"),
         "--port-base", str(port_base)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd="/root/repo",
    )
    try:
        # the runner prints one line per node: "<name>: gossip=... api=..."
        apis = {}
        deadline = time.time() + 30
        while len(apis) < 2 and time.time() < deadline:
            line = proc.stdout.readline()
            for name in ("a", "b"):
                if line.startswith(f"{name}:") and "api=" in line:
                    apis[name] = line.split("api=")[1].split()[0]
        assert set(apis) == {"a", "b"}, apis

        host_a, port_a = apis["a"].split(":")
        host_b, port_b = apis["b"].split(":")
        ca = CorrosionApiClient((host_a, int(port_a)), timeout=10.0)
        cb = CorrosionApiClient((host_b, int(port_b)), timeout=10.0)

        def ready(c):
            try:
                c.query("SELECT 1")
                return True
            except Exception:
                return False

        deadline = time.time() + 30
        while time.time() < deadline and not (ready(ca) and ready(cb)):
            time.sleep(0.3)
        ca.execute([["INSERT INTO tests (id, text) VALUES (1, 'proc')"]])
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if cb.query("SELECT text FROM tests")[1] == [["proc"]]:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            pytest.fail("write did not converge across processes")
    finally:
        proc.send_signal(_signal.SIGTERM)
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            pytest.fail("devcluster did not tear down on SIGTERM")

    run_dir = tmp_path / "cluster"
    assert (run_dir / "a" / "corrosion.db").exists()
    assert (run_dir / "b" / "corrosion.db").exists()


def test_client_post_survives_server_side_idle_close():
    """Regression: a POST must bypass the keep-alive pool (fresh
    connection), so a pooled connection the SERVER closed while idle
    cannot fail the transaction with ClientError(0) and trigger
    spurious failover.  The stub server keeps connections alive, lets
    the test close them server-side, and counts every POST body so a
    silent double-apply would also be caught."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    sockets = []
    posts = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # keep-alive by default

        def setup(self):
            super().setup()
            sockets.append(self.connection)

        def _reply(self, obj):
            body = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._reply({"ok": True})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            posts.append(self.rfile.read(n))
            self._reply({"results": [{"rows_affected": 1}]})

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        c = CorrosionApiClient(srv.server_address)
        # a GET warms the keep-alive pool; its server side now sits
        # idle in the handler thread
        c.table_stats()
        assert len(c._pool._free) == 1
        # server-side idle close: every open connection is torn down
        # underneath the pooled client socket
        for s in list(sockets):
            try:
                s.close()
            except OSError:
                pass
        # the POST must succeed on a fresh connection — applied
        # exactly once, no ClientError(0), no failover bait
        out = c.execute([["INSERT INTO tests (id) VALUES (1)"]])
        assert out["results"][0]["rows_affected"] == 1
        assert len(posts) == 1
        # and a pooled GET after the close still works (one silent
        # fresh retry is the documented idempotent-only behavior)
        assert c.table_stats() == {"ok": True}
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(timeout=5)


def test_client_pool_reuses_and_never_retries_posts(run):
    """The keep-alive pool reuses connections across idempotent calls
    ONLY; a poisoned pooled connection cannot touch a POST at all
    (non-idempotent methods ride fresh connections and are never
    re-sent), so the transaction applies exactly once."""
    async def main():
        from corrosion_tpu.client import CorrosionApiClient

        a = await launch_test_agent()
        try:
            def drive():
                c = CorrosionApiClient(a.api_addr)
                c.execute([["INSERT INTO tests (id, text) VALUES (1, 'x')"]])
                # POSTs never enter the pool...
                assert len(c._pool._free) == 0
                # ...GETs do
                for _ in range(5):
                    c.table_stats()
                assert len(c._pool._free) >= 1  # warm reuse
                # poison the pooled connection: the next POST must not
                # even see it — kill the socket underneath it
                conn = c._pool._free[0]
                conn.sock.close()
                c.execute(
                    [["INSERT INTO tests (id, text) VALUES (2, 'y')"]]
                )
                cols, rows = c.query("SELECT count(*) FROM tests WHERE id = 2")
                assert rows[0][0] == 1  # applied exactly once
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())
