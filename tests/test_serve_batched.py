"""Batched anti-entropy serve path: wire-level parity + satellites.

The batched pipeline (range bookkeeping resolution, off-loop RO-pool
collection, coalesced framing — ``runtime._serve_full_range_batched``)
must serve BYTE-IDENTICAL streams to the per-version oracle
(``runtime._serve_version``) across every state shape the ledger can
hold: multi-table versions, sentinel deletes, overwritten versions
(read-time cleared EmptySets), cleared spans, partial buffers, and
gaps.  Randomized across >=8 seeds in tier-1.
"""

import asyncio
import os
import random

from corrosion_tpu.agent.members import MemberState
from corrosion_tpu.agent.runtime import ChangeSource
from corrosion_tpu.agent.testing import CaptureWriter, make_offline_agent
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import ActorId, SyncNeedV1, Version
from corrosion_tpu.types.change import SENTINEL_CID, Change, CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.changeset import Changeset, ChangeV1
from corrosion_tpu.agent.pack import pack_values

TABLES = ("tests", "tests2", "testsblob")


def _close(a):
    if a._serve_pool is not None:
        a._serve_pool.shutdown(wait=True)
    a.storage.close()


def _mk_change(table, pk_val, cid, val, col_version, dbv, seq, site, cl=1):
    # tests/tests2 have INTEGER pks; testsblob has a BLOB pk
    if table == "testsblob":
        pk = pack_values([str(pk_val).encode()])
    else:
        import zlib

        pk = pack_values([zlib.crc32(str(pk_val).encode()) % 1000])
    return Change(
        table=table, pk=pk, cid=cid, val=val, col_version=col_version,
        db_version=CrsqlDbVersion(dbv), seq=CrsqlSeq(seq), site_id=site,
        cl=cl,
    )


def _feed(a, actor, cs):
    a.handle_change(
        ChangeV1(actor_id=ActorId(actor), changeset=cs),
        ChangeSource.SYNC, rebroadcast=False,
    )


def _random_ledger(a, actor, rng, n_versions):
    """Drive a foreign actor's ledger through a random mix of complete,
    partial, cleared, overwriting, and deleting versions."""
    ts = a.clock.new_timestamp()
    for v in range(1, n_versions + 1):
        roll = rng.random()
        if roll < 0.10:
            continue  # gap: the version stays a need
        if roll < 0.20:
            # cleared span straight from the origin's compaction
            _feed(a, actor, Changeset.empty(
                (Version(v), Version(v)), a.clock.new_timestamp()
            ))
            continue
        table = rng.choice(TABLES)
        n_cells = rng.randint(1, 4)
        changes = []
        for seq in range(n_cells):
            if rng.random() < 0.12:
                changes.append(_mk_change(
                    table, f"pk{rng.randint(0, 11)}", SENTINEL_CID, None,
                    2 * v, v, seq, actor, cl=2 * (v % 3 + 1),
                ))
            else:
                changes.append(_mk_change(
                    table, f"pk{rng.randint(0, 11)}", "text",
                    f"v{v}s{seq}", v, v, seq, actor,
                    cl=2 * (v % 2) + 1,
                ))
        if roll < 0.32:
            # partial: buffer a strict subset of the seq range
            last_seq = n_cells + rng.randint(1, 3)
            lo = rng.randint(0, n_cells - 1)
            sub = changes[lo:n_cells]
            _feed(a, actor, Changeset.full(
                Version(v), sub, (lo, n_cells - 1), last_seq, ts
            ))
        else:
            _feed(a, actor, Changeset.full(
                Version(v), changes, (0, n_cells - 1), n_cells - 1,
                a.clock.new_timestamp(),
            ))


def _serve_bytes(a, actor, need, batched):
    async def run():
        a.config.sync_batched_serve = batched
        w = CaptureWriter()
        await a._serve_need(w, actor, need)
        return bytes(w.buf)

    return asyncio.run(run())


def _assert_parity(a, actor, need):
    oracle = _serve_bytes(a, actor, need, batched=False)
    batched = _serve_bytes(a, actor, need, batched=True)
    assert batched == oracle, (
        f"served bytes diverge for {need}: "
        f"{len(batched)} vs {len(oracle)} bytes"
    )
    return oracle


def test_randomized_range_serve_parity():
    """collect_changes(lo, hi) split-by-version == per-version
    changes_for_version output, bytes-equal encoded changesets, across
    shuffled multi-table / sentinel / partial-buffer states (8 seeds)."""
    for seed in range(8):
        rng = random.Random(seed)
        a = make_offline_agent()
        try:
            actor = bytes([seed + 1]) * 16
            n = rng.randint(24, 64)
            _random_ledger(a, actor, rng, n)
            # whole range, sub-ranges straddling state transitions, and
            # an over-clamped hostile range
            blobs = [_assert_parity(a, actor, SyncNeedV1.full(1, n + 8))]
            for _ in range(4):
                lo = rng.randint(1, n)
                hi = rng.randint(lo, n)
                blobs.append(
                    _assert_parity(a, actor, SyncNeedV1.full(lo, hi))
                )
            # the full-range serve actually produced frames that decode
            assert blobs[0], "full-range serve produced no bytes"
            msgs = [
                speedy.decode_sync_message(p)
                for p in speedy.FrameReader().feed(blobs[0])
            ]
            assert msgs and all(isinstance(m, ChangeV1) for m in msgs)
        finally:
            _close(a)


def test_cleared_span_and_empty_need_serve_parity():
    """Cleared spans serve the WHOLE enclosing span (even past the need
    boundary) and jump the cursor below it; empty-need serves per-ts
    EmptySet groups — both identical across paths."""
    a = make_offline_agent()
    try:
        actor = b"\x07" * 16
        ts = a.clock.new_timestamp()
        for v in (1, 2):
            _feed(a, actor, Changeset.full(
                Version(v),
                [_mk_change("tests", f"k{v}", "text", f"t{v}", v, v, 0,
                            actor)],
                (0, 0), 0, ts,
            ))
        # a COMPLETE per-ts cleared group: marks [3, 9] cleared AND
        # advances the watermark, so the empty-need serve below has a
        # group to send
        _feed(a, actor, Changeset.empty_set(
            [(Version(3), Version(9))], a.clock.new_timestamp()
        ))
        _feed(a, actor, Changeset.full(
            Version(10),
            [_mk_change("tests", "k10", "text", "t10", 10, 10, 0, actor)],
            (0, 0), 0, a.clock.new_timestamp(),
        ))
        # need range cuts INTO the cleared span: both paths must emit
        # the full [3, 9] empty span and continue below it
        blob = _assert_parity(a, actor, SyncNeedV1.full(4, 10))
        msgs = [
            speedy.decode_sync_message(p)
            for p in speedy.FrameReader().feed(blob)
        ]
        kinds = [
            (int(m.changeset.version) if m.changeset.is_full
             else tuple(map(int, m.changeset.versions)))
            for m in msgs
        ]
        assert kinds == [10, (3, 9)]
        # empty-need (cleared-watermark catch-up): same bytes both ways
        oracle = _serve_bytes(a, actor, SyncNeedV1.empty(None), False)
        batched = _serve_bytes(a, actor, SyncNeedV1.empty(None), True)
        assert oracle == batched and oracle
        # partial-need of a fully-known version: same bytes both ways
        oracle = _serve_bytes(
            a, actor, SyncNeedV1.partial(2, [(0, 0)]), False)
        batched = _serve_bytes(
            a, actor, SyncNeedV1.partial(2, [(0, 0)]), True)
        assert oracle == batched and oracle
    finally:
        _close(a)


def test_partial_buffer_range_serve_parity():
    """A version we only hold buffered seq chunks of serves exactly the
    held spans, identically on both paths, inside a range need."""
    a = make_offline_agent()
    try:
        actor = b"\x08" * 16
        ts = a.clock.new_timestamp()
        _feed(a, actor, Changeset.full(
            Version(1),
            [_mk_change("tests", "p", "text", "x", 1, 1, 0, actor)],
            (0, 0), 0, ts,
        ))
        # v2: buffered seqs [2, 4] of last_seq 9 — incomplete
        chunk = [
            _mk_change("tests2", f"q{i}", "text", f"y{i}", 2, 2, i, actor)
            for i in (2, 3, 4)
        ]
        _feed(a, actor, Changeset.full(Version(2), chunk, (2, 4), 9, ts))
        assert 2 in a.bookie.for_actor(actor).partials
        blob = _assert_parity(a, actor, SyncNeedV1.full(1, 2))
        msgs = [
            speedy.decode_sync_message(p)
            for p in speedy.FrameReader().feed(blob)
        ]
        # newest first: the buffered span of v2, then v1
        assert [int(m.changeset.version) for m in msgs] == [2, 1]
        assert tuple(map(int, msgs[0].changeset.seqs)) == (2, 4)
        assert len(msgs[0].changeset.changes) == 3
    finally:
        _close(a)


def test_generate_sync_snapshot_cache():
    """The handshake snapshot is reused until bookkeeping mutates, then
    rebuilt — and the rebuilt state sees the mutation."""
    a = make_offline_agent()
    try:
        actor = b"\x09" * 16
        _feed(a, actor, Changeset.full(
            Version(1),
            [_mk_change("tests", "c", "text", "z", 1, 1, 0, actor)],
            (0, 0), 0, a.clock.new_timestamp(),
        ))
        st1 = a.generate_sync()
        assert a.generate_sync() is st1  # cache hit: same snapshot
        assert a.metrics.get_counter(
            "corro_sync_state_cache_total", hit="true") >= 1
        _feed(a, actor, Changeset.full(
            Version(3),
            [_mk_change("tests", "d", "text", "w", 3, 3, 0, actor)],
            (0, 0), 0, a.clock.new_timestamp(),
        ))
        st2 = a.generate_sync()
        assert st2 is not st1
        aid = ActorId(actor)
        assert int(st2.heads[aid]) == 3
        assert st2.need[aid] == [(2, 2)]  # the gap the mutation opened
    finally:
        _close(a)


def test_choose_sync_peers_skips_quarantined_and_breaker_open():
    """A quarantined (or breaker-open) member cannot absorb a sync
    round: it never enters the candidate pool."""
    from types import SimpleNamespace

    a = make_offline_agent()
    try:
        good = os.urandom(16)
        bad = os.urandom(16)
        broken = os.urandom(16)
        a.members.upsert(good, ("127.0.0.1", 1001))
        a.members.upsert(bad, ("127.0.0.1", 1002))
        a.members.upsert(broken, ("127.0.0.1", 1003))
        a.members.get(bad).quarantined = True
        a.transport = SimpleNamespace(breakers={
            ("127.0.0.1", 1003): SimpleNamespace(is_open=True),
        })
        ours = a.generate_sync()
        for _ in range(20):
            chosen = {m.actor_id for m in a._choose_sync_peers(ours)}
            assert bad not in chosen
            assert broken not in chosen
            assert good in chosen
        # restored members come back
        a.members.get(bad).quarantined = False
        a.transport.breakers.clear()
        chosen = {m.actor_id for m in a._choose_sync_peers(ours)}
        assert {good, bad, broken} <= chosen
    finally:
        a.transport = None
        _close(a)


def test_clear_buffered_meta_chunked_lock():
    """The chunked sweep (lock released between chunks) still deletes
    every buffered row of cleared versions."""
    a = make_offline_agent()
    try:
        actor = b"\x0a" * 16
        ts = a.clock.new_timestamp()
        for v in (1, 2, 3):
            chunk = [
                _mk_change("tests", f"m{v}-{i}", "text", "b", v, v, i,
                           actor)
                for i in range(3)
            ]
            _feed(a, actor, Changeset.full(Version(v), chunk, (0, 2), 9,
                                           ts))
        rows = a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_buffered_changes"
        ).fetchone()[0]
        assert rows == 9
        _feed(a, actor, Changeset.empty(
            (Version(1), Version(3)), a.clock.new_timestamp()
        ))
        deleted = a._clear_buffered_meta(chunk=2)  # force many windows
        assert deleted >= 9
        rows = a.storage.conn.execute(
            "SELECT COUNT(*) FROM __corro_buffered_changes"
        ).fetchone()[0]
        assert rows == 0
    finally:
        _close(a)


def test_capacity_rejection_counted():
    """A capacity rejection increments
    corro_sync_rejections_sent_total{reason=capacity}."""
    a = make_offline_agent()
    try:
        async def run():
            a._sync_sem = asyncio.Semaphore(0)  # .locked() -> True
            w = CaptureWriter()
            await a._serve_sync(None, w)
            return bytes(w.buf)

        blob = asyncio.run(run())
        msgs = [
            speedy.decode_sync_message(p)
            for p in speedy.FrameReader().feed(blob)
        ]
        assert msgs == [("rejection", speedy.REJECTION_MAX_CONCURRENCY)]
        assert a.metrics.get_counter(
            "corro_sync_rejections_sent_total", reason="capacity") == 1
    finally:
        _close(a)
