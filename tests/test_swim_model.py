import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.swim import (
    ALIVE,
    DOWN,
    SUSPECT,
    SwimParams,
    key_inc,
    key_state,
    swim_init,
    swim_step,
)


def _run(n, ticks, alive_fn, params=None, seed=0):
    p = params or SwimParams(n_nodes=n)
    st = swim_init(n)
    key = jax.random.PRNGKey(seed)
    for t in range(ticks):
        st = swim_step(st, jax.random.fold_in(key, t), jnp.int32(t), p, alive_fn(t))
    return st, p


def test_stable_cluster_stays_alive():
    n = 16
    st, _ = _run(n, 20, lambda t: jnp.ones((n,), bool))
    states = np.asarray(key_state(st.view))
    assert (states == ALIVE).all(), "no false suspicions without loss"


def test_dead_node_detected_down():
    n = 16
    victim = 3
    alive = jnp.ones((n,), bool).at[victim].set(False)
    st, p = _run(n, 40, lambda t: alive)
    col = np.asarray(key_state(st.view[:, victim]))
    others = np.arange(n) != victim
    assert (col[others] == DOWN).all(), "every live node must learn the death"


def test_false_suspicion_refuted_by_incarnation():
    # with packet loss but everyone alive, suspicions happen but must be
    # refuted: no live node may end up marked down with high probability
    n = 16
    p = SwimParams(n_nodes=n, loss=0.15, suspect_timeout=8)
    st, _ = _run(n, 60, lambda t: jnp.ones((n,), bool), params=p, seed=1)
    states = np.asarray(key_state(st.view))
    frac_down = (states == DOWN).mean()
    assert frac_down < 0.02, f"too many false downs: {frac_down}"
    # refutation requires incarnation bumps to have happened
    assert int(st.incarnation.max()) > 0


def test_rejoin_after_down():
    n = 16
    victim = 2
    kill, revive = 2, 30

    def alive_fn(t):
        a = jnp.ones((n,), bool)
        return a.at[victim].set(not (kill <= t < revive))

    st, p = _run(n, 80, alive_fn)
    col = np.asarray(key_state(st.view[:, victim]))
    others = np.arange(n) != victim
    assert (col[others] == ALIVE).all(), "renewed identity must propagate"
    assert int(st.incarnation[victim]) > 0, "rejoin bumps incarnation"


def test_messages_bounded_per_tick():
    # msgs/node/tick is bounded by probe + indirect + gossip budget
    n = 32
    p = SwimParams(n_nodes=n)
    st, _ = _run(n, 10, lambda t: jnp.ones((n,), bool), params=p)
    per_tick = np.asarray(st.msgs).mean() / 10
    bound = (
        2  # ping + ack
        + p.num_indirect_probes * 3
        + p.gossip_targets
    )
    assert per_tick <= bound
