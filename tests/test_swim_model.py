import jax
import jax.numpy as jnp
import pytest
import numpy as np

from corrosion_tpu.models.swim import (
    ALIVE,
    DOWN,
    SUSPECT,
    SwimParams,
    key_inc,
    key_state,
    swim_init,
    swim_step,
)


def _run(n, ticks, alive_fn, params=None, seed=0):
    p = params or SwimParams(n_nodes=n)
    st = swim_init(n)
    key = jax.random.PRNGKey(seed)
    for t in range(ticks):
        st = swim_step(st, jax.random.fold_in(key, t), jnp.int32(t), p, alive_fn(t))
    return st, p


def test_stable_cluster_stays_alive():
    n = 16
    st, _ = _run(n, 20, lambda t: jnp.ones((n,), bool))
    states = np.asarray(key_state(st.view))
    assert (states == ALIVE).all(), "no false suspicions without loss"


def test_dead_node_detected_down():
    n = 16
    victim = 3
    alive = jnp.ones((n,), bool).at[victim].set(False)
    st, p = _run(n, 40, lambda t: alive)
    col = np.asarray(key_state(st.view[:, victim]))
    others = np.arange(n) != victim
    assert (col[others] == DOWN).all(), "every live node must learn the death"


def test_false_suspicion_refuted_by_incarnation():
    # with packet loss but everyone alive, suspicions happen but must be
    # refuted: no live node may end up marked down with high probability
    n = 16
    p = SwimParams(n_nodes=n, loss=0.15, suspect_timeout=8)
    st, _ = _run(n, 60, lambda t: jnp.ones((n,), bool), params=p, seed=1)
    states = np.asarray(key_state(st.view))
    frac_down = (states == DOWN).mean()
    assert frac_down < 0.02, f"too many false downs: {frac_down}"
    # refutation requires incarnation bumps to have happened
    assert int(st.incarnation.max()) > 0


def test_rejoin_after_down():
    n = 16
    victim = 2
    kill, revive = 2, 30

    def alive_fn(t):
        a = jnp.ones((n,), bool)
        return a.at[victim].set(not (kill <= t < revive))

    st, p = _run(n, 80, alive_fn)
    col = np.asarray(key_state(st.view[:, victim]))
    others = np.arange(n) != victim
    assert (col[others] == ALIVE).all(), "renewed identity must propagate"
    assert int(st.incarnation[victim]) > 0, "rejoin bumps incarnation"


def test_messages_bounded_per_tick():
    # msgs/node/tick is bounded by probe + indirect + gossip budget
    n = 32
    p = SwimParams(n_nodes=n)
    st, _ = _run(n, 10, lambda t: jnp.ones((n,), bool), params=p)
    per_tick = np.asarray(st.msgs).mean() / 10
    bound = (
        2  # ping + ack
        + p.num_indirect_probes * 3
        + p.gossip_targets
    )
    assert per_tick <= bound


def test_update_backlog_decays_then_refreshes():
    """Freshness piggyback (foca's update queue): a stable cluster's
    entries saturate at the retransmission limit and stop circulating;
    a record change resets its counter to fresh."""
    n = 16
    p = SwimParams(n_nodes=n, update_tx_limit=4)
    st, _ = _run(n, 12, lambda t: jnp.ones((n,), bool), params=p)
    tx = np.asarray(st.update_tx)
    # an entry stops CIRCULATING once past the limit: selection gates
    # on pre-tick counts, so one tick's gossip + probe/ack piggyback
    # channels can overshoot by a few sends (the host does the same —
    # every datagram carrying the entry charges it once), but a
    # saturated backlog must then freeze entirely
    assert tx.max() <= p.update_tx_limit + 8  # loose: a popular probe
    # target acks (and charges) once per prober in the same tick
    # most entries have decayed out by now (each node charges
    # gossip_entries per tick over n peers)
    assert (tx >= p.update_tx_limit).mean() > 0.5
    st_more, _ = _run(n, 24, lambda t: jnp.ones((n,), bool), params=p)
    st_even, _ = _run(n, 30, lambda t: jnp.ones((n,), bool), params=p)
    assert np.array_equal(
        np.asarray(st_more.update_tx), np.asarray(st_even.update_tx)
    ), "saturated backlog kept charging"
    # kill a node: detectors' records change and become fresh again
    st2 = st
    key = jax.random.PRNGKey(9)
    alive = jnp.ones((n,), bool).at[3].set(False)
    for t in range(12, 24):
        st2 = swim_step(
            st2, jax.random.fold_in(key, t), jnp.int32(t), p, alive
        )
    col_states = np.asarray(key_state(st2.view[:, 3]))
    others = np.arange(n) != 3
    assert (col_states[others] != ALIVE).any(), "death must be noticed"


def test_scaled_params_grow_with_cluster():
    from corrosion_tpu.utils.swimscale import (
        scaled_suspect_timeout,
        scaled_update_retransmissions,
        swim_scale_factor,
    )

    assert swim_scale_factor(3) == 1
    assert swim_scale_factor(64) == 2
    assert swim_scale_factor(512) == 3
    assert swim_scale_factor(100_000) == 6
    # suspicion deadline: configured floor wins for small clusters,
    # the scaled term takes over as membership grows
    assert scaled_suspect_timeout(2.0, 0.4, 3) == 2.0
    assert scaled_suspect_timeout(2.0, 0.4, 64) == pytest.approx(3.2)
    assert scaled_suspect_timeout(2.0, 0.4, 512) == pytest.approx(4.8)
    assert scaled_update_retransmissions(64) == 8
    # the model's scaled constructor uses the same terms
    p = SwimParams.scaled(64)
    assert p.suspect_timeout == 8 and p.update_tx_limit == 8


def test_agent_suspect_deadline_scales(run_async=None):
    import asyncio

    from corrosion_tpu.agent.testing import launch_test_agent

    async def main():
        a = await launch_test_agent()
        try:
            base = a._suspect_deadline()  # tiny cluster: floor
            assert base == a.config.suspect_timeout
            for i in range(99):
                a.members.upsert(bytes([i]) * 16, ("127.0.0.1", 1000 + i))
            grown = a._suspect_deadline()
            # 100 members: factor 3 -> 4 * 3 * probe_interval
            assert grown == pytest.approx(
                max(a.config.suspect_timeout,
                    4 * 3 * a.config.probe_interval)
            )
            assert grown >= base
        finally:
            await a.stop()

    asyncio.run(main())
