"""Integration test: the REAL CLI binary end to end.

Parity: the reference's ``integration-tests`` crate spawns the compiled
``corrosion`` binary against a live agent (``cli_test.rs:8-51``).  Here
the binary is ``python -m corrosion_tpu.cli``: one subprocess runs
``agent`` from a TOML config; further subprocesses drive it with
``exec`` / ``query`` / ``cluster members`` / ``cluster rejoin`` exactly
as an operator would.
"""

import json
import os
import signal
import socket
import subprocess
import threading
import sys
import time

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cli(*argv, timeout=30):
    return subprocess.run(
        [sys.executable, "-m", "corrosion_tpu.cli", *argv],
        capture_output=True, text=True, timeout=timeout,
        cwd="/root/repo",
    )


@pytest.fixture
def live_agent(tmp_path):
    api_port = _free_port()
    schema = tmp_path / "schema.sql"
    schema.write_text(
        "CREATE TABLE IF NOT EXISTS tests ("
        " id INTEGER NOT NULL PRIMARY KEY,"
        " text TEXT NOT NULL DEFAULT '');"
    )
    admin_path = str(tmp_path / "admin.sock")
    cfg = tmp_path / "config.toml"
    cfg.write_text(
        f"""
[db]
path = "{tmp_path}/corrosion.db"
schema_paths = ["{schema}"]

[api]
addr = "127.0.0.1:{api_port}"

[gossip]
addr = "127.0.0.1:0"

[admin]
path = "{admin_path}"
"""
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "corrosion_tpu.cli", "agent", "-c", str(cfg)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd="/root/repo",
    )
    # wait for the startup banner
    deadline = time.time() + 30
    line = ""
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "api=" in line:
            break
    else:
        proc.kill()
        pytest.fail(f"agent did not start: {proc.stderr.read()[:2000]}")
    yield {"api": f"127.0.0.1:{api_port}", "admin": admin_path,
           "proc": proc, "banner": line, "schema": str(schema)}
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cli_against_live_agent(live_agent):
    api = live_agent["api"]

    out = _cli("--api-addr", api, "exec",
               "INSERT INTO tests (id, text) VALUES (7, 'from-cli')")
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["results"][0]["rows_affected"] == 1

    out = _cli("--api-addr", api, "query", "--columns",
               "SELECT id, text FROM tests")
    assert out.returncode == 0, out.stderr
    assert out.stdout.splitlines() == ["id\ttext", "7\tfrom-cli"]

    # admin surface over the UDS
    out = _cli("--admin-path", live_agent["admin"], "cluster", "members")
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout) == []  # no peers: empty membership

    out = _cli("--admin-path", live_agent["admin"], "cluster", "rejoin")
    assert out.returncode == 0, out.stderr
    assert "announced" in json.loads(out.stdout)

    # SIGHUP re-reads the schema files and applies additions
    # (the reference's `corrosion reload` + SIGHUP path)
    schema_path = live_agent["schema"]
    with open(schema_path, "a") as f:
        f.write(
            "\nCREATE TABLE IF NOT EXISTS hupped ("
            " id INTEGER NOT NULL PRIMARY KEY,"
            " note TEXT DEFAULT '');"
        )
    live_agent["proc"].send_signal(signal.SIGHUP)
    deadline = time.time() + 15
    while time.time() < deadline:
        out = _cli("--api-addr", api, "exec",
                   "INSERT INTO hupped (id, note) VALUES (1, 'via hup')")
        if out.returncode == 0:
            break
        time.sleep(0.3)
    else:
        pytest.fail(f"hupped table never appeared: {out.stdout} {out.stderr}")
    out = _cli("--api-addr", api, "query", "SELECT note FROM hupped")
    assert out.returncode == 0 and "via hup" in out.stdout

    # SIGTERM shuts the agent down cleanly (tripwire parity)
    proc = live_agent["proc"]
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=15) == 0


def test_sighup_reload_under_write_load(live_agent):
    """SIGHUP schema reload while writes are in flight: the reload runs
    off the event loop, so concurrent writes keep landing DURING the
    reload window and the new table appears without wedging the agent."""
    from corrosion_tpu.client import ClientError, CorrosionApiClient

    host, port = live_agent["api"].split(":")
    client = CorrosionApiClient((host, int(port)), timeout=30.0)

    stop = threading.Event()
    errors = []
    wrote = [0]

    transient = [0]

    def writer():
        i = 1000
        while not stop.is_set():
            try:
                client.execute(
                    [[f"INSERT INTO tests (id, text) VALUES ({i}, 'w')"]]
                )
                wrote[0] += 1
            except (OSError, ClientError) as e:
                # connect-phase failures surface as ClientError(0);
                # mid-response resets as raw OSError — both are
                # retryable under machine load, like any real HTTP
                # client treats them.  The insert may have committed
                # before the reset, so the id must advance (a same-id
                # retry would trip the primary key).
                if isinstance(e, ClientError) and e.status != 0:
                    errors.append(repr(e))
                    return
                transient[0] += 1
                if transient[0] > 5:
                    errors.append(f"too many transient resets: {e!r}")
                    return
                i += 1
                time.sleep(0.1)
                continue
            except Exception as e:  # noqa: BLE001 - surfaced via errors
                errors.append(repr(e))
                return
            i += 1
            time.sleep(0.02)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        with open(live_agent["schema"], "a") as f:
            f.write(
                "\nCREATE TABLE IF NOT EXISTS hup_load ("
                " id INTEGER NOT NULL PRIMARY KEY);"
            )
        wrote_at_hup = wrote[0]
        last_probe = None
        hup_t0 = time.time()
        live_agent["proc"].send_signal(signal.SIGHUP)
        deadline = hup_t0 + 60
        while time.time() < deadline:
            try:
                client.execute([["INSERT OR IGNORE INTO hup_load (id) VALUES (1)"]])
                break
            except (ClientError, OSError) as probe_err:
                last_probe = repr(probe_err)
                time.sleep(0.3)
        else:
            pytest.fail(
                f"hup_load never appeared (writer errs: {errors}, "
                f"last probe: {last_probe})"
            )
        reload_elapsed = time.time() - hup_t0
        wrote_during = wrote[0] - wrote_at_hup
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors
    # if the reload took real time, writes must have advanced during it
    # (a regression serializing the whole reload against the write path
    # would show a long window with zero writer progress); an instant
    # reload leaves no window to measure
    assert reload_elapsed < 2.0 or wrote_during >= 1, (
        reload_elapsed, wrote_during)
    # and the agent is not wedged afterwards
    client.execute([["INSERT INTO tests (id, text) VALUES (999999, 'post')"]])
