"""TLS gossip/sync stream tests: cert tooling + mTLS cluster.

Parity: reference ``corrosion tls ca/server/client generate``
(``crates/corrosion/src/main.rs:707-760``) and rustls-secured gossip
(``api/peer.rs:128-318``).  Plaintext stays the default everywhere
else in the suite.
"""

import asyncio
import socket
import ssl

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


@pytest.fixture
def certs(tmp_path):
    """CA + server cert (valid for 127.0.0.1) + client cert via the
    same code paths the CLI uses."""
    pytest.importorskip(
        "cryptography",
        reason="cert GENERATION needs the cryptography package "
               "(serving existing PEM files is stdlib-only)",
    )
    from corrosion_tpu.agent.tls import (
        generate_ca, generate_client_cert, generate_server_cert,
    )

    d = str(tmp_path)
    ca_cert, ca_key = generate_ca(d)
    srv_cert, srv_key = generate_server_cert(
        d, ca_cert, ca_key, ["127.0.0.1", "localhost"]
    )
    cli_cert, cli_key = generate_client_cert(d, ca_cert, ca_key)
    return {
        "ca": ca_cert, "ca_key": ca_key,
        "server": srv_cert, "server_key": srv_key,
        "client": cli_cert, "client_key": cli_key,
    }


def test_cli_tls_generate_without_cryptography_is_actionable(
    tmp_path, capsys, monkeypatch,
):
    """Satellite regression: on hosts without the ``cryptography``
    package (this container, deliberately), every ``tls ... generate``
    command must exit 1 with an actionable install hint — never a raw
    ModuleNotFoundError traceback from deep inside ``agent/tls.py``."""
    import builtins
    import sys as _sys

    from corrosion_tpu.cli import main

    real_import = builtins.__import__

    def no_crypto(name, *a, **kw):
        if name == "cryptography" or name.startswith("cryptography."):
            raise ModuleNotFoundError(
                "No module named 'cryptography'", name="cryptography"
            )
        return real_import(name, *a, **kw)

    # simulate absence even where the package IS installed (and drop
    # any cached modules so the block actually bites)
    for mod in [m for m in _sys.modules if m.startswith("cryptography")]:
        monkeypatch.delitem(_sys.modules, mod)
    monkeypatch.setattr(builtins, "__import__", no_crypto)

    d = str(tmp_path)
    for argv in (
        ["tls", "ca", "generate", "--dir", d],
        ["tls", "server", "generate", "127.0.0.1", "--dir", d],
        ["tls", "client", "generate", "--dir", d],
    ):
        assert main(argv) == 1
        err = capsys.readouterr().err
        assert "cryptography" in err
        assert "pip install cryptography" in err
        assert "Traceback" not in err


def test_cli_tls_generate(tmp_path):
    pytest.importorskip("cryptography")
    from corrosion_tpu.cli import main

    d = str(tmp_path)
    assert main(["tls", "ca", "generate", "--dir", d]) == 0
    # --ca-cert/--ca-key default to <dir>/ca.{crt,key}
    assert main(["tls", "server", "generate", "127.0.0.1", "--dir", d]) == 0
    assert main(["tls", "client", "generate", "--dir", d]) == 0
    # both leaf certs genuinely verify against the CA's signature
    from cryptography import x509

    with open(f"{d}/ca.crt", "rb") as f:
        ca = x509.load_pem_x509_certificate(f.read())
    for leaf_name in ("server.crt", "client.crt"):
        with open(f"{d}/{leaf_name}", "rb") as f:
            leaf = x509.load_pem_x509_certificate(f.read())
        leaf.verify_directly_issued_by(ca)  # raises on a bad chain


def test_mtls_cluster_converges(run, certs):
    """A 2-node cluster with mutual TLS on every gossip/sync stream
    still converges; the wire genuinely refuses plaintext."""
    async def main():
        tls_kw = dict(
            tls_cert_file=certs["server"],
            tls_key_file=certs["server_key"],
            tls_ca_file=certs["ca"],
            tls_client_required=True,
        )
        a = await launch_test_agent(**tls_kw)
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"], **tls_kw
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'secret')"]]
            )
            await wait_for(
                lambda: b.storage.read_query(
                    "SELECT text FROM tests WHERE id=1"
                )[1] == [("secret",)],
                timeout=15,
            )
            # sync path too: an isolated later write heals over TLS
            b.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'back')"]]
            )
            await wait_for(
                lambda: a.storage.read_query(
                    "SELECT count(*) FROM tests"
                )[1] == [(2,)],
                timeout=15,
            )

            # a plaintext TCP client gets no gossip service
            with socket.create_connection(tuple(a.gossip_addr),
                                          timeout=5) as s:
                s.sendall(b"\x00" * 64)
                s.settimeout(5)
                try:
                    data = s.recv(1024)
                except (ConnectionError, socket.timeout):
                    data = b""
                assert data == b""  # TLS server rejects, never speaks
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_tls_without_client_cert_rejected(run, certs):
    """With tls_client_required, a TLS client that presents no client
    cert cannot complete a stream handshake (mTLS is enforced)."""
    async def main():
        a = await launch_test_agent(
            tls_cert_file=certs["server"],
            tls_key_file=certs["server_key"],
            tls_ca_file=certs["ca"],
            tls_client_required=True,
        )
        try:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE

            def try_connect():
                with socket.create_connection(tuple(a.gossip_addr),
                                              timeout=5) as raw:
                    with ctx.wrap_socket(raw) as s:
                        # TLS 1.3: the certificate-required alert lands
                        # on the first read/write after the handshake —
                        # as an SSLError or as an abrupt empty read
                        s.sendall(b"x")
                        return s.recv(64)

            try:
                data = await asyncio.to_thread(try_connect)
            except (ssl.SSLError, ConnectionError, OSError):
                data = b""
            assert data == b"", "server served a certless client"
        finally:
            await a.stop()

    run(main())
