"""Snapshot bootstrap: build/stage/install units, scrub-registry
coverage, crash-recovery windows, maintenance-driven compaction, the
bootstrap-equivalence parity suite, and the live two-node wire path.

The parity discipline mirrors PRs 3-5: the change-by-change path is
the oracle — a node bootstrapped via snapshot install + tail sync must
converge to canonically-equal table state, row clocks, and contained
bookkeeping against always-alive nodes that applied every change
individually (docs/sync.md, "Snapshot serve + install").
"""

from __future__ import annotations

import asyncio
import os
import sqlite3

import pytest

from corrosion_tpu.agent import snapshot as snaplib
from corrosion_tpu.agent.runtime import Agent, AgentConfig
from corrosion_tpu.agent.testing import (
    TEST_SCHEMA,
    launch_test_agent,
    wait_for,
)


def _offline_agent(tmp_path, name, **kw) -> Agent:
    return Agent(AgentConfig(
        db_path=str(tmp_path / f"{name}.db"), schema_sql=TEST_SCHEMA,
        **kw,
    ))


def _tables(conn) -> set:
    return {
        r[0]
        for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table'"
        )
    }


# ---------------------------------------------------------------------------
# scrub registry: every live __corro_* table must have a decision
# ---------------------------------------------------------------------------


def test_scrub_registry_covers_live_schema(tmp_path):
    """The regression the shared registry exists for: every internal
    table in a LIVE agent database classifies keep-or-scrub — a future
    bookkeeping table with no decision fails here instead of silently
    leaking into (or vanishing from) snapshots and backups."""
    a = _offline_agent(tmp_path, "a")
    internal = [
        t for t in _tables(a.storage.conn) if t.startswith("__corro_")
    ]
    assert internal, "expected internal tables in a live schema"
    for t in internal:
        assert snaplib.classify_table(t) in ("keep", "scrub"), t
    # the decisions the satellite names: the digest FIFO is node-local
    # cache (scrub); signed proofs are portable cluster evidence (keep)
    assert snaplib.classify_table("__corro_equiv_digests") == "scrub"
    assert snaplib.classify_table("__corro_equiv_proofs") == "keep"
    assert snaplib.classify_table("__corro_members") == "scrub"
    assert snaplib.classify_table("__corro_bookkeeping") == "keep"
    # the backfill queue is PORTABLE: its table rows travel unversioned
    # in the copy, so without the entry the receiver's boot-time
    # _register_backfills would never version them
    assert snaplib.classify_table("__corro_backfills") == "keep"
    assert snaplib.classify_table("tests__corro_clock") == "keep"
    assert snaplib.classify_table("tests") is None
    with pytest.raises(snaplib.SnapshotError):
        snaplib.classify_table("__corro_未registered")
    a.storage.close()


def test_backup_scrubs_through_registry(tmp_path):
    """backup.py predated the PR 7/13 bookkeeping tables; it now
    shares the snapshot registry: digests scrub, proofs survive."""
    from corrosion_tpu.agent.backup import backup

    a = _offline_agent(tmp_path, "a")
    a.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'kept-row')",)]
    )
    a.storage.conn.execute(
        "INSERT INTO __corro_equiv_digests "
        "(actor_id, version, digest) VALUES (x'01', 1, x'aa')"
    )
    a.storage.conn.execute(
        "INSERT INTO __corro_equiv_proofs "
        "(actor_id, version, kind, msg_a, sig_a, msg_b, sig_b) "
        "VALUES (x'01', 1, 'content', x'bb', x'bb', x'cc', x'cc')"
    )
    out = str(tmp_path / "backup.db")
    backup(a.config.db_path, out)
    c = sqlite3.connect(out)
    assert c.execute("SELECT count(*) FROM tests").fetchone()[0] == 1
    assert c.execute(
        "SELECT count(*) FROM __corro_equiv_digests"
    ).fetchone()[0] == 0
    assert c.execute(
        "SELECT count(*) FROM __corro_equiv_proofs"
    ).fetchone()[0] == 1
    assert c.execute(
        "SELECT count(*) FROM __corro_members"
    ).fetchone()[0] == 0
    c.close()
    a.storage.close()


# ---------------------------------------------------------------------------
# build / digest / crash-recovery windows
# ---------------------------------------------------------------------------


def test_build_snapshot_scrubs_and_single_file(tmp_path):
    a = _offline_agent(tmp_path, "a")
    a.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'snap-me')",)]
    )
    out = str(tmp_path / "snap.db")
    snaplib.build_snapshot(a.config.db_path, out)
    # single-file artifact: DELETE journal mode, no -wal sidecar
    assert not os.path.exists(out + "-wal")
    c = sqlite3.connect(out)
    assert c.execute(
        "PRAGMA journal_mode"
    ).fetchone()[0].lower() == "delete"
    assert c.execute("SELECT count(*) FROM tests").fetchone()[0] == 1
    assert c.execute(
        "SELECT count(*) FROM __corro_members"
    ).fetchone()[0] == 0
    assert c.execute(
        "SELECT count(*) FROM __corro_state WHERE key='incarnation'"
    ).fetchone()[0] == 0
    c.close()
    # target-exists refuses (the serve cache swaps via a tmp name)
    with pytest.raises(snaplib.SnapshotError):
        snaplib.build_snapshot(a.config.db_path, out)
    digest = snaplib.file_digest(out)
    assert len(digest) == snaplib.DIGEST_LEN
    assert digest == snaplib.file_digest(out)
    a.storage.close()


def test_recovery_windows_classify(tmp_path):
    """Every crash window of the install state machine boots into
    exactly one of two outcomes (docs/sync.md, crash-recovery
    contract): retry-from-scratch or finalized."""
    db = str(tmp_path / "node.db")
    with open(db, "w") as f:
        f.write("previous database")

    # no marker, no sidecar: nothing pending
    assert snaplib.recover_pending_install(db) is None

    # orphan sidecar, no marker: crash before the first marker write
    with open(snaplib.staged_path(db), "w") as f:
        f.write("partial stream")
    assert snaplib.recover_pending_install(db) == "retry"
    assert not os.path.exists(snaplib.staged_path(db))

    # staging marker + sidecar present: mid-stream or verified-but-
    # unswapped — discard both, previous database untouched
    snaplib.write_marker(db, "staging", b"\x00" * 32, 123)
    with open(snaplib.staged_path(db), "w") as f:
        f.write("partial stream")
    assert snaplib.recover_pending_install(db) == "retry"
    assert not os.path.exists(snaplib.staged_path(db))
    assert snaplib.read_marker(db) is None
    with open(db) as f:
        assert f.read() == "previous database"

    # installing marker + sidecar STILL present: the swap never ran
    snaplib.write_marker(db, "installing", b"\x00" * 32, 123)
    with open(snaplib.staged_path(db), "w") as f:
        f.write("prepared but unswapped")
    assert snaplib.recover_pending_install(db) == "retry"

    # installing marker + sidecar gone: os.replace completed — the DB
    # IS the snapshot; stale -wal/-shm of the REPLACED inode removed
    snaplib.write_marker(db, "installing", b"\x00" * 32, 123)
    with open(db + "-wal", "w") as f:
        f.write("stale wal of the replaced inode")
    assert snaplib.recover_pending_install(db) == "finalized"
    assert not os.path.exists(db + "-wal")
    assert snaplib.read_marker(db) is None


# ---------------------------------------------------------------------------
# offline stage + install end-to-end (the runtime helpers, no wire)
# ---------------------------------------------------------------------------


def _serve_blob(server):
    path, digest, size = server._snapshot_build()
    with open(path, "rb") as f:
        return f.read(), digest, size


def test_offline_install_end_to_end(tmp_path):
    """Stage + verify + identity rewrite + atomic swap + in-place
    reload: the installing node ends with the server's data, its OWN
    site id at ordinal 1, and a working write path."""
    a1 = _offline_agent(tmp_path, "a1")
    for i in range(5):
        a1.execute_transaction(
            [("INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
              (i % 2, f"v{i}"))]
        )
    a2 = _offline_agent(tmp_path, "a2")
    blob, digest, size = _serve_blob(a1)

    st = a2._snapshot_stage_begin("peer", digest, size, {})
    a2._snapshot_stage_feed(st, blob)
    assert a2._snapshot_install_staged(st) is True
    assert snaplib.read_marker(a2.config.db_path) is None
    assert not os.path.exists(snaplib.staged_path(a2.config.db_path))

    _, rows = a2.storage.read_query(
        "SELECT id, text FROM tests ORDER BY id"
    )
    assert rows == [(0, "v4"), (1, "v3")]
    # identity: ordinal 1 is the INSTALLING node, the origin keeps its
    # clock attribution under a fresh ordinal
    sites = dict(a2.storage.conn.execute(
        "SELECT ordinal, site_id FROM __corro_sites"
    ))
    assert bytes(sites[1]) == a2.actor_id
    assert any(
        bytes(s) == a1.actor_id for o, s in sites.items() if o != 1
    )
    # bookkeeping rode the snapshot: a2 holds a1's ledger
    bv = a2.bookie.for_actor(a1.actor_id)
    assert bv.last() == 5
    assert all(bv.contains_version(v) for v in range(1, 6))
    # the write path works against the installed file (triggers +
    # version cursor intact)
    r = a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (77, 'post-install')",)]
    )
    assert r["version"] == 1
    assert a2.metrics.get_counter(
        "corro_snapshot_installs_total", result="ok"
    ) == 1
    a1.storage.close()
    a2.storage.close()


def test_install_rejects_digest_mismatch(tmp_path):
    """The containment gate: truncated, corrupted, or divergent-minted
    bytes die on the whole-snapshot digest with a clean abort — the
    previous database untouched, marker gone, breaker-visible
    reason=snap_digest counted."""
    a1 = _offline_agent(tmp_path, "a1")
    a1.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'truth')",)]
    )
    a2 = _offline_agent(tmp_path, "a2")
    a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (2, 'mine')",)]
    )
    blob, digest, size = _serve_blob(a1)
    heads = {a2.actor_id: 1}  # the server's recorded view of a2

    # truncate
    st = a2._snapshot_stage_begin("peer", digest, size, heads)
    a2._snapshot_stage_feed(st, blob[: len(blob) // 2])
    assert a2._snapshot_install_staged(st) is False
    # corrupt one byte (same size, honest digest advertised)
    st = a2._snapshot_stage_begin("peer", digest, size, heads)
    a2._snapshot_stage_feed(
        st, blob[:100] + bytes([blob[100] ^ 0xFF]) + blob[101:]
    )
    assert a2._snapshot_install_staged(st) is False
    # oversized stream dies while staging
    st = a2._snapshot_stage_begin("peer", digest, size, heads)
    with pytest.raises(snaplib.SnapshotError):
        a2._snapshot_stage_feed(st, blob + b"x")
    a2._snapshot_abort(st, "snap_stream")

    assert a2.metrics.get_counter(
        "corro_sync_client_rejects_total", reason="snap_digest"
    ) == 2
    assert snaplib.read_marker(a2.config.db_path) is None
    _, rows = a2.storage.read_query("SELECT id, text FROM tests")
    assert rows == [(2, "mine")]  # previous database untouched
    r = a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (3, 'still-alive')",)]
    )
    assert r["version"] == 2
    a1.storage.close()
    a2.storage.close()


def test_install_aborts_on_local_write_races(tmp_path):
    """The install-safety re-check under the storage lock: a local
    write committed after dispatch (own head beyond the server's
    recorded limit) aborts the swap instead of being silently lost."""
    a1 = _offline_agent(tmp_path, "a1")
    a1.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'server')",)]
    )
    a2 = _offline_agent(tmp_path, "a2")
    blob, digest, size = _serve_blob(a1)
    st = a2._snapshot_stage_begin("peer", digest, size, {})
    a2._snapshot_stage_feed(st, blob)
    # the race: a local write lands mid-transfer
    a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (9, 'local-only')",)]
    )
    assert a2._snapshot_install_staged(st) is False
    assert a2.metrics.get_counter(
        "corro_sync_client_rejects_total", reason="snap_stale"
    ) == 1
    _, rows = a2.storage.read_query("SELECT id, text FROM tests")
    assert rows == [(9, "local-only")]
    a1.storage.close()
    a2.storage.close()


def test_failed_swap_restores_a_working_connection(tmp_path, monkeypatch):
    """A swap that raises (the disk-full / EXDEV shape, injected at
    ``os.replace``) must never leave a LIVE agent bricked: storage
    comes back up on the previous database and the runtime re-points
    every in-memory view at the restored connection — reads AND
    writes work afterwards."""
    a1 = _offline_agent(tmp_path, "a1")
    a1.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'server')",)]
    )
    a2 = _offline_agent(tmp_path, "a2")
    a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (2, 'before')",)]
    )
    blob, digest, size = _serve_blob(a1)
    st = a2._snapshot_stage_begin(
        "peer", digest, size, {a2.actor_id: 1}
    )
    a2._snapshot_stage_feed(st, blob)

    real_replace = os.replace

    def failing_replace(src, dst):
        if dst == a2.config.db_path:
            raise OSError(28, "No space left on device")
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", failing_replace)
    with pytest.raises(OSError):
        a2._snapshot_install_staged(st)
    monkeypatch.setattr(os, "replace", real_replace)
    # the previous database is live again — reads AND writes (which
    # go through the Bookie's connection) both work
    _, rows = a2.storage.read_query("SELECT id, text FROM tests")
    assert rows == [(2, "before")]
    r = a2.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (3, 'after')",)]
    )
    assert r["version"] == 2
    a1.storage.close()
    a2.storage.close()


def test_serve_handle_survives_cache_rebuild(tmp_path):
    """The offer/stream TOCTOU: a serve slower than ``snapshot_cache_s``
    must keep streaming the inode its offer advertised — the handle
    opens under the build lock, so a concurrent rebuild replacing the
    cache path cannot divert the stream onto bytes that fail the
    client's digest gate."""
    import hashlib

    a = _offline_agent(tmp_path, "a", snapshot_cache_s=0.0)
    a.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (1, 'gen-1')",)]
    )
    f, digest, size = a._snapshot_build_open()
    # a newer build replaces the cache file before the slow serve
    # reads a single byte
    a.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (2, 'gen-2')",)]
    )
    f2, digest2, _ = a._snapshot_build_open()
    f2.close()
    assert digest2 != digest  # the cache genuinely moved on
    blob = f.read()
    f.close()
    assert len(blob) == size
    assert hashlib.blake2b(
        blob, digest_size=snaplib.DIGEST_LEN
    ).digest() == digest
    a.storage.close()


# ---------------------------------------------------------------------------
# history compaction: floors, contained prefix, idle-node maintenance
# ---------------------------------------------------------------------------


def test_contained_prefix_bounds():
    from corrosion_tpu.agent.bookkeeping import BookedVersions

    bv = BookedVersions(b'\x01' * 16)
    bv.max_version = 10
    assert bv.contained_prefix() == 10
    bv.needed.insert(4, 6)
    assert bv.contained_prefix() == 3
    bv.needed.remove(4, 6)
    # a partial at v=2 caps the prefix below it
    bv.partials = {2: None}
    assert bv.contained_prefix() == 1


def test_set_snap_floor_compacts_ledger_and_extends_head():
    from corrosion_tpu.agent.bookkeeping import BookedVersions

    bv = BookedVersions(b'\x01' * 16)
    bv.versions = {1: (1, 1), 2: (2, 2), 5: (5, 5)}
    bv.max_version = 5
    bv.set_snap_floor(3)
    assert bv.snap_floor == 3
    assert set(bv.versions) == {5}
    assert bv.contains_version(1) and bv.contains_version(3)
    # a floor record ABOVE max_version re-extends the head (the reload
    # path: concrete rows below the floor were compacted away)
    bv2 = BookedVersions(b'\x02' * 16)
    bv2.set_snap_floor(7)
    assert bv2.last() == 7
    assert bv2.contains_version(7)
    assert not bv2.contains_version(8)


def test_idle_node_floor_advances_and_persists(tmp_path):
    """The satellite regression: an idle-but-serving node's sweep is
    maintenance-driven (``_compaction_pass``), not post-commit — the
    floor advances with NO write in flight, persists, compacts the
    per-version rows, and reloads across restart."""
    a = _offline_agent(
        tmp_path, "a", snapshot_retain_versions=0,
    )
    for i in range(8):
        a.execute_transaction(
            [("INSERT INTO tests (id, text) VALUES (?, 'h')", (i,))]
        )
    rows_before = a.storage.conn.execute(
        "SELECT count(*) FROM __corro_bookkeeping WHERE actor_id=?",
        (a.actor_id,),
    ).fetchone()[0]
    assert rows_before >= 1
    # idle: no write between the history and the sweep
    cleared = a._compaction_pass()
    assert cleared >= 1
    bv = a.bookie.for_actor(a.actor_id)
    assert bv.snap_floor == 8
    assert a.metrics.get_counter_sum(
        "corro_compaction_maintenance_clears_total"
    ) >= 1
    assert a.storage.conn.execute(
        "SELECT count(*) FROM __corro_bookkeeping WHERE actor_id=? "
        "AND end_version IS NULL",
        (a.actor_id,),
    ).fetchone()[0] == 0
    assert a.storage.conn.execute(
        "SELECT floor FROM __corro_snap_floors WHERE actor_id=?",
        (a.actor_id,),
    ).fetchone()[0] == 8
    # advertised in the handshake
    st = a.generate_sync()
    from corrosion_tpu.types import ActorId

    assert st.snap_floors.get(ActorId(a.actor_id)) == 8
    # second sweep with nothing new: no further advance
    assert a._advance_snapshot_floors() == 0
    a.storage.close()

    # restart: the floor reloads and the head survives compaction
    b = _offline_agent(tmp_path, "a", snapshot_retain_versions=0)
    bv = b.bookie.for_actor(b.actor_id)
    assert bv.snap_floor == 8
    assert bv.last() == 8
    r = b.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (100, 'next')",)]
    )
    assert r["version"] == 9
    b.storage.close()


def test_retain_window_holds_floor_back(tmp_path):
    a = _offline_agent(tmp_path, "a", snapshot_retain_versions=5)
    for i in range(8):
        a.execute_transaction(
            [("INSERT INTO tests (id, text) VALUES (?, 'h')", (i,))]
        )
    a._compaction_pass()
    assert a.bookie.for_actor(a.actor_id).snap_floor == 3
    # negative disables advancement entirely
    a.config.snapshot_retain_versions = -1
    assert a._advance_snapshot_floors() == 0
    a.storage.close()


# ---------------------------------------------------------------------------
# snapshot-or-changes dispatch: pure functions
# ---------------------------------------------------------------------------


def test_dispatch_pure_functions():
    from corrosion_tpu.types.payload import SyncNeedV1

    needs = {
        "a": [SyncNeedV1.full(1, 10)],
        "b": [SyncNeedV1.partial(3, [(0, 4)])],
    }
    # floors cover: actor a compacted through 6 -> 6 versions of the
    # full span plus the partial below b's floor
    assert snaplib.covered_below_floor(
        needs, {"a": 6, "b": 4}
    ) == 7
    assert snaplib.covered_below_floor(needs, {"a": 0}) == 0
    assert snaplib.covered_below_floor({}, {"a": 6}) == 0
    # needs strictly above the floor: changes can still deliver them
    assert snaplib.covered_below_floor(
        {"a": [SyncNeedV1.full(7, 10)]}, {"a": 6}
    ) == 0

    assert snaplib.client_behind({"x": 3}, {"x": 3, "y": 9})
    assert snaplib.client_behind({}, {"x": 1})
    # a local-only write makes the install unsound
    assert not snaplib.client_behind({"x": 4}, {"x": 3})


# ---------------------------------------------------------------------------
# wire: snap message variants + the sync-state floor extension
# ---------------------------------------------------------------------------


def test_snap_wire_roundtrip():
    from corrosion_tpu.bridge import speedy

    for msg in (
        ("snap_request",),
        ("snap_offer", bytes(range(32)), 123456),
        ("snap_chunk", b"some snapshot bytes"),
        ("snap_done",),
    ):
        enc = speedy.encode_sync_message(msg)
        out = speedy.decode_sync_message(enc)
        assert out[0] == msg[0]
        if msg[0] == "snap_offer":
            assert bytes(out[1]) == msg[1] and out[2] == msg[2]
        if msg[0] == "snap_chunk":
            assert bytes(out[1]) == msg[1]
    with pytest.raises(speedy.SpeedyError):
        speedy.encode_sync_message(("snap_offer", b"\x00" * 31, 1))
    # truncated offer rejects instead of mis-decoding
    enc = speedy.encode_sync_message(
        ("snap_offer", bytes(32), 7)
    )
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_sync_message(enc[: len(enc) - 2])


def test_sync_state_floor_extension_bytes():
    """Floor-less states emit the pre-extension bytes exactly (the
    trailing-map discipline of last_cleared_ts before it); states with
    floors round-trip them."""
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.types import ActorId
    from corrosion_tpu.types.payload import SyncStateV1

    actor = ActorId(bytes(range(16)))
    peer = ActorId(bytes(range(16, 32)))
    base = SyncStateV1(actor_id=actor, heads={peer: 9})
    enc_plain = speedy.encode_sync_message(base)
    st = speedy.decode_sync_message(enc_plain)
    assert st.snap_floors == {}

    floored = SyncStateV1(
        actor_id=actor, heads={peer: 9}, snap_floors={peer: 7}
    )
    enc_floor = speedy.encode_sync_message(floored)
    assert enc_floor[: len(enc_plain)] == enc_plain  # pure suffix
    assert len(enc_floor) > len(enc_plain)
    st2 = speedy.decode_sync_message(enc_floor)
    assert st2.snap_floors == {peer: 7}


# ---------------------------------------------------------------------------
# bootstrap-equivalence parity: snapshot+tail vs change-by-change
# ---------------------------------------------------------------------------


def _canonical_state(a) -> dict:
    """Site-ordinal-independent dump of every CRR table + its clock/cl
    tables: ordinals map through __corro_sites to site ids, so two
    nodes with different site directories compare bytewise."""
    sites = {
        o: bytes(s).hex()
        for o, s in a.storage.conn.execute(
            "SELECT ordinal, site_id FROM __corro_sites"
        )
    }
    out = {}
    for t in sorted(a.storage.tables):
        q = t.replace('"', '""')
        rows = a.storage.conn.execute(f'SELECT * FROM "{q}"').fetchall()
        out[t] = sorted(repr(r) for r in rows)
        for sfx in ("__corro_clock", "__corro_cl"):
            ct = t + sfx
            cols = [
                r[1]
                for r in a.storage.conn.execute(
                    f'PRAGMA table_info("{ct}")'
                )
            ]
            if not cols:
                continue
            si = cols.index("site_ordinal") if "site_ordinal" in cols \
                else None
            canon = []
            for r in a.storage.conn.execute(
                f'SELECT * FROM "{ct}"'
            ):
                r = list(r)
                if si is not None:
                    r[si] = sites[r[si]]
                canon.append(repr(r))
            out[ct] = sorted(canon)
    return out


def _contained_ledgers(a) -> dict:
    """Per-actor contained view: head + the exact contained set +
    unresolved partials (the applied/cleared/floored split is a
    per-node compaction detail and deliberately NOT compared)."""
    out = {}
    for actor, bv in a.bookie.actors().items():
        head = bv.last()
        if head == 0 and not bv.partials:
            continue
        out[actor.hex()] = (
            head,
            tuple(
                v for v in range(1, head + 1)
                if bv.contains_version(v)
            ),
            tuple(sorted(
                int(v) for v, p in bv.partials.items()
                if p is not None and not p.is_complete()
            )),
        )
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_bootstrap_equivalence_parity(tmp_path, seed):
    """One cluster, randomized history (overwrites -> cleared spans
    crossing the floor, an unresolved foreign partial riding the
    ledger), floors compacted everywhere, a victim wiped to a fresh
    bootstrap.  Seeds 1-3 kill the installer at each journal stage
    (faults.SnapFault) so the retry path is part of the parity claim.
    End state: the snapshot+tail node is canonically EQUAL to the
    always-alive change-by-change nodes — tables, row clocks, and
    contained ledgers."""
    import random

    from corrosion_tpu.faults import (
        CrashEvent,
        EquivocatingPeer,
        FaultPlan,
        SnapFault,
    )
    from corrosion_tpu.sim.vcluster import VirtualCluster
    from corrosion_tpu.types import ChangeSource
    from corrosion_tpu.types.base import CrsqlSeq

    stage = [None, "crash_staging", "crash_installing",
             "crash_swapped"][seed]
    victim = "n5"
    plan = FaultPlan(
        seed=seed,
        crashes=(CrashEvent(victim, at=0.1, restart_at=0.6),),
        snap_faults=() if stage is None else (
            SnapFault(victim, stage, restart_delay=0.3),
        ),
    )
    rng = random.Random(seed)
    c = VirtualCluster(
        6, seed=seed, plan=plan, base_dir=str(tmp_path),
        defer_crashes=True, snapshot_retain_versions=0,
    )
    try:
        versions = []
        for w in range(10):
            origin = rng.choice([0, 1, 2])
            # overwrites: a few distinct ids rewritten repeatedly so
            # the originating ledgers grow cleared spans
            row = rng.choice([1, 2, 3, 50 + w])
            v = c.write(
                origin,
                "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                (row, f"par-{seed}-{w}"),
            )
            versions.append((c.agents[f"n{origin}"].actor_id, v))
            c.run_for(0.03)
        # an unresolved PARTIAL from a foreign actor, buffered on every
        # node: first seq half of a two-seq version
        peer = EquivocatingPeer(
            seed=900 + seed, now_ns=c.clock.wall_ns
        )
        half = peer._changeset(
            1, 7001, f"partial-{seed}",
            seqs=(CrsqlSeq(0), CrsqlSeq(0)), last_seq=CrsqlSeq(1),
        )
        c.inject(list(range(6)), half, ChangeSource.BROADCAST,
                 rebroadcast=False)
        assert c.run_until_true(
            lambda: c.converged(versions), timeout=30
        )
        # floors advance over the full contained history on every node
        # (cleared spans from the overwrites sit BELOW the floor)
        for a in c.agents.values():
            a._compaction_pass()
        own = c.agents["n0"].bookie.for_actor(
            c.agents["n0"].actor_id
        )
        assert own.snap_floor > 0
        assert own.cleared.spans(), "history must hold cleared spans"

        t0 = c.clock.monotonic()
        c.schedule_plan_crashes(t0)
        c.schedule_wipe(victim, t0 + 0.35)
        # tail writes: committed while the victim is dead, so they sit
        # ABOVE the server floors — only the tail sync can deliver them
        tail = []
        for w in range(3):
            v = c.write(
                0, "INSERT INTO tests (id, text) VALUES (?, ?)",
                (9100 + w, f"tail-{seed}-{w}"),
            )
            tail.append((c.agents["n0"].actor_id, v))
            c.run_for(0.05)

        want_events = 2 + (2 if stage is not None else 0)
        assert c.run_until_true(
            lambda: len(c.ctrl.crash_log) >= want_events
            and not c._crashed
            and c.converged(versions + tail),
            timeout=40,
        ), (c.ctrl.crash_log, c._crashed)
        c.run_for(0.3)

        reborn = c.agents[victim]
        if stage in (None, "crash_staging", "crash_installing"):
            # these windows recover by RETRYING the install
            assert reborn.metrics.get_counter(
                "corro_snapshot_installs_total", result="ok"
            ) >= 1
        if stage is not None:
            assert c.ctrl.injected["snap_crash"] == 1

        ref = _canonical_state(c.agents["n0"])
        led_ref = _contained_ledgers(c.agents["n0"])
        assert _canonical_state(reborn) == ref
        assert _contained_ledgers(reborn) == led_ref
        # the foreign partial survived the bootstrap on BOTH paths
        assert 1 in reborn.bookie.for_actor(peer.actor_id).partials

        # completing the partial later applies identically everywhere
        from dataclasses import replace

        from corrosion_tpu.types import ChangeV1

        other = peer._changeset(
            1, 7002, f"partial-{seed}-tail",
            seqs=(CrsqlSeq(1), CrsqlSeq(1)), last_seq=CrsqlSeq(1),
            seq=1,
        )
        # one version = one commit ts: both halves share the stamp
        other = ChangeV1(
            other.actor_id,
            replace(other.changeset, ts=half.changeset.ts),
        )
        c.inject(list(range(6)), other, ChangeSource.BROADCAST,
                 rebroadcast=False)
        c.run_for(0.5)
        assert _canonical_state(reborn) == _canonical_state(
            c.agents["n0"]
        )
        assert c.observer().no_divergence()["ok"]
    finally:
        c.close()


# ---------------------------------------------------------------------------
# live wire: the real serve/install path over sockets
# ---------------------------------------------------------------------------


def test_live_snapshot_bootstrap(tmp_path):
    """Two REAL agents: the server's floor covers its whole history,
    a fresh node bootstraps — the sync round dispatches snap_request,
    the serve streams chunked frames through the coalesced sync
    framing, the client stages + verifies + swaps, and the tail write
    arrives via normal anti-entropy afterwards."""
    async def main():
        (tmp_path / "n1").mkdir()
        (tmp_path / "n2").mkdir()
        a1 = await launch_test_agent(
            tmpdir=str(tmp_path / "n1"),
            snapshot_retain_versions=0,
        )
        for i in range(10):
            a1.execute_transaction(
                [("INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                  (i % 3, f"live-{i}"))]
            )
        a1._compaction_pass()
        floor = a1.bookie.for_actor(a1.actor_id).snap_floor
        assert floor == 10
        # drain the broadcast retransmission tail: a node joining
        # IMMEDIATELY after the writes would catch the recent payloads
        # via gossip and never need the snapshot — the scenario under
        # test is the long-dead/new node whose history is floor-only
        await asyncio.sleep(2.0)
        a2 = await launch_test_agent(
            bootstrap=[f"{a1.gossip_addr[0]}:{a1.gossip_addr[1]}"],
            tmpdir=str(tmp_path / "n2"),
            snapshot_retain_versions=0,
        )
        await wait_for(
            lambda: a2.metrics.get_counter(
                "corro_snapshot_installs_total", result="ok"
            ) >= 1,
            timeout=20,
        )
        assert a1.metrics.get_counter(
            "corro_snapshot_serves_total"
        ) >= 1

        def table_equal():
            q = "SELECT id, text FROM tests ORDER BY id"
            return (a2.storage.read_query(q)[1]
                    == a1.storage.read_query(q)[1])

        await wait_for(table_equal, timeout=20)
        # bookkeeping rode along: a2 holds a1's contained history
        bv = a2.bookie.for_actor(a1.actor_id)
        assert all(bv.contains_version(v) for v in range(1, 11))
        # tail: a post-install write reaches a2 via normal gossip/sync
        a1.execute_transaction(
            [("INSERT INTO tests (id, text) VALUES (500, 'tail')",)]
        )
        await wait_for(
            lambda: a2.storage.read_query(
                "SELECT text FROM tests WHERE id=500"
            )[1] == [("tail",)],
            timeout=20,
        )
        await a1.stop()
        await a2.stop()

    asyncio.run(main())
