"""Native kernels vs their pure-Python twins: exact behavioral match.

The native module (corrosion_tpu/native/_corrosion_native.cc) carries
the hottest host-runtime paths; these tests pin it to the Python
implementations on randomized inputs so the two can never drift.  When
no toolchain is available the module is absent and the suite still
passes (the package falls back to Python everywhere).
"""

import random

import pytest

from corrosion_tpu.agent import pack
from corrosion_tpu.native import load

native = load()

pytestmark = pytest.mark.skipif(
    native is None, reason="no C++ toolchain: Python fallback in use"
)


def _rand_value(rng):
    kind = rng.randrange(6)
    if kind == 0:
        return None
    if kind == 1:
        return rng.randint(-(2**62), 2**62)
    if kind == 2:
        return rng.random() * 1e6 - 5e5
    if kind == 3:
        return "".join(
            chr(rng.randrange(1, 0x250)) for _ in range(rng.randrange(12))
        )
    if kind == 4:
        return bytes(rng.randrange(256) for _ in range(rng.randrange(12)))
    return bool(rng.randrange(2))


def test_pack_unpack_matches_python():
    rng = random.Random(11)
    for _ in range(300):
        vals = [_rand_value(rng) for _ in range(rng.randrange(5))]
        pb = pack._py_pack_values(vals)
        nb = native.pack_values(vals)
        assert nb == pb, vals
        assert native.unpack_values(pb) == pack._py_unpack_values(pb)


def test_unpack_error_parity():
    # truncated payloads and bad tags raise the same way
    good = native.pack_values([1, "abc"])
    for cut in range(1, len(good)):
        try:
            py = pack._py_unpack_values(good[:cut])
        except ValueError:
            py = ValueError
        try:
            nat = native.unpack_values(good[:cut])
        except ValueError:
            nat = ValueError
        assert nat == py, cut
    with pytest.raises(ValueError, match="bad tag"):
        native.unpack_values(b"\x09")
    with pytest.raises(TypeError):
        native.pack_values([object()])


def test_value_cmp_matches_python():
    rng = random.Random(12)
    vals = [_rand_value(rng) for _ in range(60)]
    for a in vals:
        for b in vals:
            assert native.value_cmp(a, b) == pack._py_value_cmp(a, b), (a, b)
    # total-order sanity: INTEGER > FLOAT > TEXT > BLOB > NULL
    assert native.value_cmp(0, 1e9) == 1
    assert native.value_cmp(1.0, "zzz") == 1
    assert native.value_cmp("", b"\xff") == 1
    assert native.value_cmp(b"", None) == 1


def test_deframe_matches_python():
    from corrosion_tpu.bridge import speedy

    rng = random.Random(13)
    payloads = [
        bytes(rng.randrange(256) for _ in range(rng.randrange(40)))
        for _ in range(20)
    ]
    stream = b"".join(speedy.frame(p) for p in payloads)
    # every prefix split must agree between native and Python
    for cut in range(0, len(stream), 7):
        nf, nr = native.deframe(stream[:cut], speedy.MAX_FRAME_LEN)
        pf, pr = speedy._py_deframe(stream[:cut])
        assert nf == pf and nr == pr, cut
    with pytest.raises(ValueError):
        native.deframe(b"\xff\xff\xff\xff rest", speedy.MAX_FRAME_LEN)


def test_agent_paths_use_native():
    """The hot call sites actually resolve to the native functions."""
    assert pack.pack_values is native.pack_values
    assert pack.unpack_values is native.unpack_values
    from corrosion_tpu.bridge import speedy

    assert speedy.deframe is not speedy._py_deframe


def test_pack_rejects_nonstandard_buffers_and_big_ints():
    """Divergence guards: objects the Python twin rejects must fail the
    same way natively (array.array is a buffer but NOT a SQL value)."""
    import array

    with pytest.raises(TypeError):
        native.pack_values([array.array("b", [1, 2])])
    with pytest.raises(TypeError):
        pack._py_pack_values([array.array("b", [1, 2])])
    with pytest.raises(OverflowError):
        native.pack_values([2**70])
    with pytest.raises(OverflowError):
        pack._py_pack_values([2**70])


def test_speedy_change_codec_matches_python():
    """Native speedy change-array encode/decode is byte- and
    value-identical to the pure-Python twin on random changes."""
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
    from corrosion_tpu.types.change import Change

    rng = random.Random(77)
    changes = []
    for i in range(200):
        changes.append(Change(
            table=rng.choice(["tests", "tbl_ü", "x"]),
            pk=bytes(rng.randrange(256) for _ in range(rng.randrange(1, 20))),
            cid=rng.choice(["text", "-1", "cöl"]),
            val=_rand_value(rng),
            col_version=rng.randint(0, 2**40),
            db_version=CrsqlDbVersion(rng.randint(0, 2**40)),
            seq=CrsqlSeq(i),
            site_id=bytes(rng.randrange(256) for _ in range(16)),
            cl=rng.randrange(1, 9),
        ))

    # encode: native bytes == python bytes
    nat = native.speedy_encode_changes(changes)
    w = speedy.Writer()
    for c in changes:
        speedy._w_change(w, c)
    assert nat == w.getvalue()

    # decode: native tuples reconstruct the identical changes
    r = speedy.Reader(nat)
    out = speedy._r_changes(r, len(changes))
    assert r.pos == len(nat)
    # bools encode as ints on the wire (SqliteValue has no bool)
    def norm(c):
        v = int(c.val) if isinstance(c.val, bool) else c.val
        return (c.table, c.pk, c.cid, v, c.col_version, int(c.db_version),
                int(c.seq), c.site_id, c.cl)
    assert [norm(c) for c in out] == [norm(c) for c in changes]

    # truncation surfaces as SpeedyError, like the Python reader
    with pytest.raises(speedy.SpeedyError):
        speedy._r_changes(speedy.Reader(nat[:-3]), len(changes))


def test_speedy_change_codec_edge_parity():
    """u64-domain versions, bytes-like values, and hostile offsets:
    native and Python twins agree byte-for-byte or fail alike."""
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
    from corrosion_tpu.types.change import Change

    c = Change(table="t", pk=bytearray(b"\x01\x02"), cid="c",
               val=bytearray(b"ab"), col_version=1,
               db_version=CrsqlDbVersion(2**63 + 5),
               seq=CrsqlSeq(2**64 - 2), site_id=bytes(16), cl=1)
    nat = native.speedy_encode_changes([c])
    w = speedy.Writer()
    speedy._w_change(w, c)
    assert nat == w.getvalue()
    out = speedy._r_changes(speedy.Reader(nat), 1)[0]
    assert int(out.db_version) == 2**63 + 5
    assert int(out.seq) == 2**64 - 2

    with pytest.raises(ValueError):
        native.speedy_decode_changes(nat, -4, 1)
    with pytest.raises(ValueError):
        native.speedy_decode_changes(nat, len(nat) + 1, 1)
