"""Metrics exposition contract: cumulative histogram aggregates, strict
Prometheus text parsing under hostile input, and the doc-drift lint
keeping docs/telemetry.md and the emitted `corro_*` series in lockstep.
"""

from __future__ import annotations

import ast
import pathlib
import re

import pytest

from corrosion_tpu.agent.metrics import (
    ExpositionError,
    Metrics,
    parse_prometheus_text,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _series(text: str, family: str):
    return parse_prometheus_text(text)[family]["samples"]


# -- cumulative histogram aggregates -----------------------------------


def test_histogram_count_monotone_past_trim_boundary():
    """Regression: `_count`/`_sum` were computed over the trimmed
    1024-sample quantile ring, silently resetting after 1024
    observations.  They must be cumulative — a Prometheus summary's
    `_count` is monotone by contract."""
    m = Metrics()
    n = 1500  # past the 1024-sample ring trim
    for i in range(n):
        m.histogram("corro_test_seconds", float(i))
    samples = _series(m.render(), "corro_test_seconds")
    by_name = {name: v for name, _l, v in samples}
    assert by_name["corro_test_seconds_count"] == float(n)
    assert by_name["corro_test_seconds_sum"] == float(sum(range(n)))
    # the quantile ring stays windowed (the trim is the point of it);
    # block trimming keeps it between 1024 and 1279 samples
    assert 1024 <= len(m.histogram_samples("corro_test_seconds")[()]) < 1280
    # and the cumulative stats surface matches the exposition
    assert m.histogram_stats("corro_test_seconds") == (n, float(sum(range(n))))


def test_histogram_count_monotone_across_renders():
    m = Metrics()
    m.histogram("corro_test_seconds", 1.0)
    first = {n: v for n, _l, v in _series(m.render(), "corro_test_seconds")}
    m.histogram("corro_test_seconds", 2.0)
    second = {n: v for n, _l, v in _series(m.render(), "corro_test_seconds")}
    assert second["corro_test_seconds_count"] > first["corro_test_seconds_count"]
    assert second["corro_test_seconds_sum"] > first["corro_test_seconds_sum"]


# -- strict parsing + hostile exposition -------------------------------


def test_hostile_label_values_roundtrip_through_strict_parser():
    """Adversarial label values — quotes, backslashes, newlines — must
    render escaped and parse back to the original strings."""
    hostile = 'we"ird\\ta\nble'
    m = Metrics()
    m.counter("corro_test_total", table=hostile)
    m.gauge("corro_test_gauge", 7.0, who='a"b', other="c\\d")
    m.histogram("corro_test_seconds", 0.5, kind="x\ny")
    text = m.render(
        extra_gauges=[("corro_table_rows", 3.0, {"table": hostile})]
    )
    fams = parse_prometheus_text(text)
    assert fams["corro_test_total"]["samples"][0][1] == {"table": hostile}
    assert fams["corro_table_rows"]["samples"][0][1] == {"table": hostile}
    glabels = fams["corro_test_gauge"]["samples"][0][1]
    assert glabels == {"who": 'a"b', "other": "c\\d"}
    hsamples = fams["corro_test_seconds"]["samples"]
    assert all(l["kind"] == "x\ny" for _n, l, _v in hsamples)


def test_extra_gauge_merges_into_registered_family():
    """A scrape-time extra gauge sharing a name with a registered gauge
    renders under ONE `# TYPE` line (strict parsers reject a repeated
    TYPE) and the scrape-time value wins."""
    m = Metrics()
    m.gauge("corro_members_ring0", 1.0)
    text = m.render(extra_gauges=[("corro_members_ring0", 4.0, {})])
    assert text.count("# TYPE corro_members_ring0 gauge") == 1
    fams = parse_prometheus_text(text)  # raises on a repeated TYPE
    assert fams["corro_members_ring0"]["samples"] == [
        ("corro_members_ring0", {}, 4.0)
    ]


@pytest.mark.parametrize(
    "bad",
    [
        "# TYPE corro_x gauge\n# TYPE corro_x gauge\ncorro_x 1\n",
        "corro_orphan 1\n",  # sample without a TYPE declaration
        "# TYPE corro_x gauge\ncorro_x{l=\"a\nb\"} 1\n",  # raw newline
        "# TYPE corro_x gauge\ncorro_x{l=\"a\\qb\"} 1\n",  # bad escape
        "# TYPE corro_x gauge\ncorro_x{l=\"ab} 1\n",  # unterminated
        "# TYPE corro_x gauge\ncorro_x nope\n",  # junk value
        "# TYPE corro_x wat\ncorro_x 1\n",  # unknown type
        "# TYPE 9bad gauge\n",  # bad family name
    ],
)
def test_parser_rejects_malformed_exposition(bad):
    with pytest.raises(ExpositionError):
        parse_prometheus_text(bad)


def test_adversarial_table_names_rejected_cleanly():
    """The CRR machinery interpolates table/column names into
    bookkeeping DDL and cached hot-path SQL — a hostile schema (user
    input) with a quoted table or column name must be rejected as a
    clean SchemaError at apply time, not surface as a SQL syntax error
    mid-introspection (the pre-plane behavior)."""
    from corrosion_tpu.agent.schema import SchemaError, parse_schema

    for evil in (
        'CREATE TABLE "ev""il" (id INTEGER NOT NULL PRIMARY KEY);',
        'CREATE TABLE "sp ace" (id INTEGER NOT NULL PRIMARY KEY);',
        'CREATE TABLE t (id INTEGER NOT NULL PRIMARY KEY, "c""ol" TEXT'
        " NOT NULL DEFAULT '');",
    ):
        with pytest.raises(SchemaError):
            parse_schema(evil)


def test_agent_scrape_parses_under_strict_parser(tmp_path):
    """A live offline agent's full /metrics render — registry series
    plus every scrape-time extra gauge (table rows, queue depths,
    staleness, transport aggregates) — passes the strict parser."""
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        a.execute_transaction(
            [("INSERT INTO tests (id, text) VALUES (1, 'x')", ())]
        )
        fams = parse_prometheus_text(a.metrics.render(a.metric_gauges()))
        rows = {
            labels["table"]: v
            for _n, labels, v in fams["corro_table_rows"]["samples"]
        }
        assert rows["tests"] == 1.0
    finally:
        a.storage.close()


def test_unknown_swim_kind_clamps_and_parses(tmp_path):
    """A hostile SWIM datagram kind must not mint an unbounded (or
    unparseable) label series: unknown kinds clamp to `other`."""
    import json

    from corrosion_tpu.agent.runtime import _UdpProtocol
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        proto = _UdpProtocol(a)
        evil = 'evil"kind\nwith\\junk'
        proto.datagram_received(
            json.dumps({"c": 0, "k": evil, "pb": []}).encode(),
            ("127.0.0.1", 1),
        )
        fams = parse_prometheus_text(a.metrics.render())
        kinds = {
            labels["kind"]
            for _n, labels, _v in fams[
                "corro_gossip_datagrams_received_total"
            ]["samples"]
        }
        assert "other" in kinds
        assert evil not in kinds
    finally:
        a.storage.close()


# -- doc-drift lint (tier-1) -------------------------------------------

# corro_*-named identifiers that are NOT metric series (SQL UDFs, a
# contextvar, an attribute name) — keep in sync with their call sites
NON_METRIC_NAMES = {
    "corro_pack",  # storage.py SQL UDF
    "corro_json_contains",  # storage.py SQL UDF
    "corro_current_span",  # tracing.py contextvar name
    "corro_conns",  # runtime.py pg server attribute
}


def _emitted_series() -> set:
    """Every `corro_*` series named in corrosion_tpu/ source: string
    literals, plus the one dynamic transport-gauge f-string expanded
    from its literal iteration tuple."""
    names = set()
    for p in sorted((REPO / "corrosion_tpu").rglob("*.py")):
        src = p.read_text()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and re.fullmatch(r"corro_[a-z0-9_]*[a-z0-9]", node.value)
            ):
                names.add(node.value)
            if isinstance(node, ast.JoinedStr):
                first = node.values[0]
                if not (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith("corro_")
                ):
                    continue
                m = re.search(
                    r"for field in \(([^)]*)\):?\s*\n[^\n]*\n\s*f\""
                    + re.escape(first.value),
                    src,
                )
                assert m, (
                    f"dynamic corro_* f-string in {p} the doc-drift "
                    "lint cannot expand — iterate a literal tuple "
                    "directly above it, or make the names literals"
                )
                for field in re.findall(r'"([a-z0-9_]+)"', m.group(1)):
                    names.add(first.value + field)
    return names - NON_METRIC_NAMES


def _documented_series() -> set:
    """Every `corro_*` series named in docs/telemetry.md backticks.
    `{a,b}` inside a name is alternation (expanded); `{k=v}` is a label
    set (stripped).  Fenced code blocks are skipped — their backticks
    would break inline pairing."""
    documented = set()
    fenced = False
    for line in (REPO / "docs" / "telemetry.md").read_text().splitlines():
        if line.lstrip().startswith("```"):
            fenced = not fenced
            continue
        if fenced:
            continue
        for tok in re.findall(r"`([^`]+)`", line):
            for m in re.finditer(
                r"corro_[a-zA-Z0-9_]*(?:\{[^}]*\}[a-zA-Z0-9_]*)*", tok
            ):
                name = m.group(0)
                variants = [""]
                pos = 0
                for bm in re.finditer(r"\{([^}]*)\}", name):
                    head = name[pos : bm.start()]
                    body = bm.group(1)
                    pos = bm.end()
                    if "=" in body:  # label braces: name ends here
                        variants = [v + head for v in variants]
                        pos = len(name)
                        break
                    variants = [
                        v + head + alt
                        for v in variants
                        for alt in body.split(",")
                    ]
                tail = name[pos:]
                for v in variants:
                    full = v + tail
                    if re.fullmatch(r"corro_[a-z0-9_]*[a-z0-9]", full):
                        documented.add(full)
    return documented


def _emitted_event_kinds() -> set:
    """Every flight-event kind LITERAL passed to an emission call
    (``_flight_event(...)`` / ``<x>.event(...)``) in corrosion_tpu/ —
    including both arms of a conditional first argument."""
    names = set()
    for p in sorted((REPO / "corrosion_tpu").rglob("*.py")):
        tree = ast.parse(p.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            callee = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if callee not in ("_flight_event", "event"):
                continue
            for sub in ast.walk(node.args[0]):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    names.add(sub.value)
    # `.event(` also matches unrelated calls; keep only kind-shaped
    # literals so e.g. a threading.Event subclass can't pollute the set
    return {n for n in names if re.fullmatch(r"[a-z][a-z0-9_]*", n)}


def _documented_event_kinds() -> set:
    """Backticked first-column cells of docs/telemetry.md's 'Flight
    event registry' table."""
    text = (REPO / "docs" / "telemetry.md").read_text()
    m = re.search(
        r"### Flight event registry\n(.*?)(?:\n#+ |\Z)", text, re.S
    )
    assert m, "docs/telemetry.md lost its 'Flight event registry' section"
    kinds = set()
    for line in m.group(1).splitlines():
        mm = re.match(r"\|\s*`([a-z][a-z0-9_]*)`\s*\|", line)
        if mm and mm.group(1) != "kind":
            kinds.add(mm.group(1))
    return kinds


def test_event_registry_docs_and_emission_in_lockstep():
    """The typed-event sibling of the series lint: every kind the
    journal can carry (recorder.EVENT_KINDS) must be emitted somewhere,
    documented in docs/telemetry.md, and nothing undeclared may be
    emitted or documented."""
    from corrosion_tpu.agent.recorder import EVENT_KINDS

    registry = set(EVENT_KINDS)
    emitted = _emitted_event_kinds()
    documented = _documented_event_kinds()
    assert registry, "empty event registry"
    undocumented = sorted(registry - documented)
    assert not undocumented, (
        "registered flight-event kinds missing from docs/telemetry.md's "
        f"event-registry table: {undocumented}"
    )
    phantom_docs = sorted(documented - registry)
    assert not phantom_docs, (
        "documented flight-event kinds absent from recorder.EVENT_KINDS: "
        f"{phantom_docs}"
    )
    unregistered = sorted(emitted - registry)
    assert not unregistered, (
        f"emission sites pass kinds outside the registry: {unregistered}"
    )
    never_emitted = sorted(registry - emitted)
    assert not never_emitted, (
        "registered kinds with no emission site in corrosion_tpu/: "
        f"{never_emitted}"
    )


def test_docs_and_emitted_series_in_lockstep():
    """Doc-drift lint: every `corro_*` series emitted in corrosion_tpu/
    must be named in docs/telemetry.md, and vice-versa — the build
    fails when metrics and docs diverge."""
    emitted = _emitted_series()
    documented = _documented_series()
    # sanity: both extractors actually found the registry
    assert len(emitted) > 50 and len(documented) > 50
    undocumented = sorted(emitted - documented)
    assert not undocumented, (
        "emitted but not in docs/telemetry.md: "
        f"{undocumented} — add rows (or extend NON_METRIC_NAMES if "
        "these are not metric series)"
    )
    phantom = sorted(documented - emitted)
    assert not phantom, (
        f"documented in docs/telemetry.md but emitted nowhere: {phantom}"
    )
