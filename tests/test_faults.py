"""Deterministic fault injection + degraded-mode hardening.

Covers the fault subsystem end to end: FaultPlan decision determinism
(same seed ⇒ byte-identical decisions), the backoff retry helper's
jitter bounds, circuit-breaker state machine + member quarantine and
restore, and live-cluster convergence THROUGH the sim's fault family —
10% link loss, a partition-heal cycle, and a crash/restart — on a
9-node in-process cluster (tier-1-fast sizes; the N≈32 soak is
``@pytest.mark.slow`` and feeds ``CHAOS_N32.json`` via
``bench.py --chaos``).
"""

import asyncio
import random
import time

import pytest

from corrosion_tpu.faults import (
    CrashEvent,
    FaultAction,
    FaultController,
    FaultPlan,
)
from corrosion_tpu.utils.backoff import Backoff, retry


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


# ---------------------------------------------------------------------------
# FaultPlan / FaultController determinism
# ---------------------------------------------------------------------------


def test_faultplan_decisions_are_byte_identical_across_replays():
    plan = FaultPlan(seed=42, drop=0.3, delay=0.01, delay_jitter=0.02)

    def trace(p):
        out = bytearray()
        for src in ("n0", "n1", "n2"):
            for dst in ("n0", "n1", "n2"):
                for ch in ("uni", "bi", "udp"):
                    for n in range(64):
                        out += p.link_decision(src, dst, ch, n).encode()
        return bytes(out)

    a, b = trace(plan), trace(plan)
    assert a == b  # pure function: replay is byte-identical
    # decisions actually vary (some drops, some passes)
    acts = [
        plan.link_decision("n0", "n1", "uni", n) for n in range(200)
    ]
    drops = sum(1 for x in acts if x.drop)
    assert 20 < drops < 120  # ~30% of 200, loose bounds
    # a different seed yields a different decision stream
    other = FaultPlan(seed=43, drop=0.3, delay=0.01, delay_jitter=0.02)
    assert trace(other) != a


def test_faultcontroller_replay_log_is_byte_identical():
    plan = FaultPlan(seed=7, drop=0.25, partition_blocks=2)

    def drive(ctrl):
        for name, addr in (("a", ("127.0.0.1", 1)), ("b", ("127.0.0.1", 2)),
                           ("c", ("127.0.0.1", 3)), ("d", ("127.0.0.1", 4))):
            ctrl.register(name, addr)
        ctrl.start()
        ctrl.split()
        hooks = {n: ctrl.hook_for(n) for n in "abcd"}
        for i in range(50):
            hooks["a"]("uni", ("127.0.0.1", 2))
            hooks["a"]("uni", ("127.0.0.1", 3))  # cross-block: partition
            hooks["c"]("udp", ("127.0.0.1", 4))
            hooks["b"]("bi", ("127.0.0.1", 1))
        ctrl.heal()
        for i in range(50):
            hooks["a"]("uni", ("127.0.0.1", 3))  # healed: seeded draws
        return bytes(ctrl.decision_log)

    log1 = drive(FaultController(plan))
    log2 = drive(FaultController(plan))
    assert log1 == log2
    assert len(log1) > 0


def test_partition_blocks_match_sim_partition_ids():
    # same index→block map as sim/epidemic._partition_ids
    plan = FaultPlan(partition_blocks=3)
    blocks = [plan.block_of(i, 9) for i in range(9)]
    assert blocks == [0, 0, 0, 1, 1, 1, 2, 2, 2]


def test_partitioned_drop_does_not_consume_link_counter():
    """Partition drops must not burn seeded draws: post-heal decisions
    depend only on the number of NON-partition messages sent."""
    plan = FaultPlan(seed=1, drop=0.5, partition_blocks=2)
    addrs = {"a": ("127.0.0.1", 1), "b": ("127.0.0.1", 2)}

    def post_heal_trace(n_partition_drops):
        ctrl = FaultController(plan)
        for n, ad in addrs.items():
            ctrl.register(n, ad)
        ctrl.start()
        ctrl.split()
        hook = ctrl.hook_for("a")
        for _ in range(n_partition_drops):
            assert hook("uni", addrs["b"]).reason == "partition"
        ctrl.heal()
        return b"".join(
            hook("uni", addrs["b"]).encode() for _ in range(20)
        )

    assert post_heal_trace(3) == post_heal_trace(11)


def test_unregistered_destination_is_never_faulted():
    ctrl = FaultController(FaultPlan(seed=0, drop=1.0))
    ctrl.register("a", ("127.0.0.1", 1))
    hook = ctrl.hook_for("a")
    act = hook("uni", ("10.0.0.9", 999))  # not a cluster node
    assert not act.drop and not act.delay


# ---------------------------------------------------------------------------
# the adversarial families: determinism + round-trip (scenario matrix)
# ---------------------------------------------------------------------------


def _adversarial_plan(seed=21):
    from corrosion_tpu.faults import LoopStall

    return FaultPlan(
        seed=seed,
        drop=0.1,
        partition_blocks=2,
        oneway_blocks=((0, 1),),
        clock_skew_max_ns=200_000_000,
        clock_drift_max_ppm=150.0,
        disk_write_delay=0.001,
        disk_write_jitter=0.002,
        disk_read_delay=0.0005,
        disk_read_jitter=0.001,
        loop_stalls=(LoopStall("n0", at=0.1, duration_ms=80.0),),
        crashes=(CrashEvent("n1", at=0.5, restart_at=1.0),),
    )


def test_new_families_replay_byte_identical():
    """The PR 2 determinism property extended to the new families: the
    full decision stream — link draws, one-way partition drops, and
    slow-disk delays — is byte-identical across replays, and per-node
    clock skew re-derives identically."""
    plan = _adversarial_plan()

    def drive(ctrl):
        for i, name in enumerate("abcd"):
            ctrl.register(name, ("127.0.0.1", i + 1))
        ctrl.start()
        ctrl.split()
        hooks = {n: ctrl.hook_for(n) for n in "abcd"}
        io = {n: ctrl.io_hook_for(n) for n in "abcd"}
        for _ in range(40):
            hooks["a"]("uni", ("127.0.0.1", 3))  # severed direction
            hooks["c"]("uni", ("127.0.0.1", 1))  # open direction
            hooks["b"]("bi", ("127.0.0.1", 4))
            io["a"]("write")
            io["c"]("read")
        ctrl.heal()
        for _ in range(20):
            hooks["a"]("uni", ("127.0.0.1", 3))
            io["a"]("write")
        return bytes(ctrl.decision_log), dict(ctrl.injected)

    log1, inj1 = drive(FaultController(plan))
    log2, inj2 = drive(FaultController(plan))
    assert log1 == log2
    assert inj1 == inj2
    assert inj1["partition"] > 0 and inj1["disk"] > 0
    # clock skew is derived, not drawn: identical across controllers,
    # distinct across nodes, bounded by the plan
    skews = [plan.node_clock(f"n{i}") for i in range(8)]
    assert skews == [plan.node_clock(f"n{i}") for i in range(8)]
    assert len({s[0] for s in skews}) > 1
    for off, drift in skews:
        assert abs(off) <= plan.clock_skew_max_ns
        assert abs(drift) <= plan.clock_drift_max_ppm * 1e-6
    # a different seed re-derives differently
    other = _adversarial_plan(seed=22)
    assert other.node_clock("n0") != plan.node_clock("n0")


def test_oneway_partition_is_directional():
    """One-way block matrices: only the listed (src_block, dst_block)
    directions sever; symmetric plans (no matrix) sever both."""
    plan = _adversarial_plan()
    ctrl = FaultController(plan)
    for i, name in enumerate(("a", "b")):  # a → block 0, b → block 1
        ctrl.register(name, ("127.0.0.1", i + 1))
    ctrl.start()
    ctrl.split()
    assert ctrl.filter("a", "b", "uni").reason == "partition"
    act = ctrl.filter("b", "a", "partition_check")
    assert not act.drop  # reverse direction open — incl. the TOCTOU probe
    ctrl.heal()
    assert ctrl.filter("a", "b", "partition_check").drop is False

    sym = FaultPlan(seed=1, partition_blocks=2)
    sctrl = FaultController(sym)
    for i, name in enumerate(("a", "b")):
        sctrl.register(name, ("127.0.0.1", i + 1))
    sctrl.start()
    sctrl.split()
    assert sctrl.filter("a", "b", "uni").reason == "partition"
    assert sctrl.filter("b", "a", "uni").reason == "partition"


def test_as_dict_round_trips_all_fault_families():
    """FaultController.as_dict → FaultPlan.from_dict reconstructs the
    identical plan (every new field included), so a replay can be
    driven from an admin `faults` dump."""
    plan = _adversarial_plan()
    ctrl = FaultController(plan)
    ctrl.register("a", ("127.0.0.1", 1))
    d = ctrl.as_dict()
    assert FaultPlan.from_dict(d) == plan
    # and it is JSON-clean (the admin socket ships it as JSON)
    import json

    assert FaultPlan.from_dict(json.loads(json.dumps(d))) == plan


def test_io_decisions_are_seeded_and_bounded():
    plan = _adversarial_plan()
    ds = [plan.io_decision("n0", "write", n) for n in range(100)]
    assert ds == [plan.io_decision("n0", "write", n) for n in range(100)]
    for d in ds:
        assert plan.disk_write_delay <= d <= (
            plan.disk_write_delay + plan.disk_write_jitter
        )
    # distinct per node and per op
    assert ds != [plan.io_decision("n1", "write", n) for n in range(100)]
    reads = [plan.io_decision("n0", "read", n) for n in range(100)]
    for d in reads:
        assert plan.disk_read_delay <= d <= (
            plan.disk_read_delay + plan.disk_read_jitter
        )


def test_storage_io_fault_seam_consults_hook(tmp_path):
    """CrConn.io_fault is consulted once per write batch and once per
    change collection — the slow-disk injection seams."""
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        calls = []
        a.storage.io_fault = lambda op: calls.append(op) or 0.0
        a.execute_transaction([
            ("INSERT INTO tests (id, text) VALUES (1, 'x')",)
        ])
        assert "write" in calls
        calls.clear()
        a.storage.collect_changes((1, 10))
        assert calls == ["read"]
    finally:
        a.storage.close()


# ---------------------------------------------------------------------------
# backoff retry helper
# ---------------------------------------------------------------------------


def test_backoff_jitter_bounds_and_determinism():
    rng = random.Random(123)
    delays = list(Backoff(base=0.1, cap=2.0, max_retries=50, rng=rng))
    assert len(delays) == 50
    prev = 0.1
    for d in delays:
        # decorrelated jitter: each delay in [base, min(cap, prev*3)]
        assert 0.1 <= d <= 2.0
        assert d <= max(0.1, prev * 3) + 1e-9
        prev = d
    # seeded rng ⇒ replayable schedule
    delays2 = list(
        Backoff(base=0.1, cap=2.0, max_retries=50,
                rng=random.Random(123))
    )
    assert delays == delays2


def test_retry_helper_bounded_and_replayable(run):
    async def main():
        calls = {"n": 0}
        slept = []

        async def fake_sleep(d):
            slept.append(d)

        async def always_fails():
            calls["n"] += 1
            raise OSError("nope")

        with pytest.raises(OSError):
            await retry(
                always_fails,
                Backoff(base=0.05, cap=0.5, max_retries=3,
                        rng=random.Random(9)),
                sleep=fake_sleep,
            )
        # max_retries bounds the RETRIES: 1 first attempt + 3 retries
        assert calls["n"] == 4
        assert len(slept) == 3
        assert all(0.05 <= d <= 0.5 for d in slept)

        # deterministic-RNG path: same seed, same failure sequence ⇒
        # same sleep schedule
        slept2 = []

        async def fake_sleep2(d):
            slept2.append(d)

        with pytest.raises(OSError):
            await retry(
                always_fails,
                Backoff(base=0.05, cap=0.5, max_retries=3,
                        rng=random.Random(9)),
                sleep=fake_sleep2,
            )
        assert slept == slept2

        # success stops retrying immediately
        state = {"n": 0}

        async def second_try():
            state["n"] += 1
            if state["n"] < 2:
                raise ConnectionError("flap")
            return "ok"

        assert await retry(
            second_try,
            Backoff(base=0.01, cap=0.05, max_retries=3,
                    rng=random.Random(1)),
            sleep=fake_sleep,
        ) == "ok"
        assert state["n"] == 2

    run(main())


# ---------------------------------------------------------------------------
# circuit breaker + member quarantine
# ---------------------------------------------------------------------------


def test_circuit_breaker_state_machine():
    from corrosion_tpu.agent.transport import CircuitBreaker

    b = CircuitBreaker(threshold=3, cooldown=0.05)
    assert b.allow() and b.state() == "closed"
    assert not b.record_failure()
    assert not b.record_failure()
    assert b.record_failure()  # third consecutive failure OPENS
    assert b.is_open and not b.allow()
    time.sleep(0.06)
    assert b.state() == "half-open"
    assert b.allow()  # one half-open trial
    assert not b.allow()  # ...only one at a time
    assert b.record_success()  # trial succeeded: breaker closes
    assert b.state() == "closed" and b.allow()
    # reopen, then a FAILED half-open trial restarts the cooldown
    for _ in range(3):
        b.record_failure()
    assert b.is_open
    time.sleep(0.06)
    assert b.allow()
    assert not b.record_failure()  # re-arms, does not double-count open
    assert not b.allow()


def test_members_quarantine_deprioritizes_and_restores():
    from corrosion_tpu.agent.members import Members

    ms = Members(b"self" * 4)
    actors = []
    for i in range(6):
        actor = bytes([i]) * 16
        actors.append(actor)
        ms.upsert(actor, ("127.0.0.1", 1000 + i))
        for _ in range(5):
            ms.record_rtt(actor, 1.0)  # everyone ring0-fast
    assert len(ms.ring0()) == 6
    bad = actors[0]
    ms.set_quarantined(bad, True)
    # quarantined: out of ring0, and never sampled while healthy peers
    # can fill k
    assert all(m.actor_id != bad for m in ms.ring0())
    rng = random.Random(0)
    for _ in range(50):
        picked = ms.sample(3, rng, ring0_first=False)
        assert all(m.actor_id != bad for m in picked)
    # ...but still used when the healthy pool cannot fill k (half-open
    # trials need traffic)
    picked = ms.sample(6, rng, ring0_first=False)
    assert len(picked) == 6
    # restore on half-open success: full eligibility returns
    ms.set_quarantined(bad, False)
    assert any(m.actor_id == bad for m in ms.ring0())
    seen_bad = any(
        any(m.actor_id == bad for m in ms.sample(3, rng, ring0_first=False))
        for _ in range(100)
    )
    assert seen_bad

    # the addr-keyed path the transport callback uses
    assert ms.quarantine_by_addr(("127.0.0.1", 1001), True)
    assert ms.get(actors[1]).quarantined
    assert ms.quarantine_by_addr(("127.0.0.1", 1001), False)
    assert not ms.get(actors[1]).quarantined
    assert not ms.quarantine_by_addr(("10.0.0.1", 1), True)


# ---------------------------------------------------------------------------
# live 9-node cluster through the fault family
# ---------------------------------------------------------------------------


async def _boot_cluster(n, tmp_path, ctrl, **overrides):
    from corrosion_tpu.devcluster import Topology, run_inprocess

    topo = Topology.parse("\n".join(f"n0 -> n{i}" for i in range(1, n)))
    kwargs = dict(
        ring0_enabled=False,
        subs_enabled=False,
        api_port=None,
        uni_cache_size=16,
        breaker_cooldown=0.3,
        redial_base=0.02,
        redial_cap=0.2,
    )
    kwargs.update(overrides)
    agents = await run_inprocess(
        topo, base_dir=str(tmp_path), faults=ctrl, **kwargs
    )
    from corrosion_tpu.agent.testing import seed_full_membership

    # membership is pre-seeded: these tests pin the DATA-plane degraded
    # paths (loss / partition / crash catch-up / breaker bounds), not
    # SWIM formation — which is covered by test_agent_gossip and the
    # soak, and would add a wall-clock flake surface here
    seed_full_membership(list(agents.values()))
    return agents


def _table(a):
    return a.storage.read_query("SELECT id, text FROM tests ORDER BY id")[1]


async def _stop_all(agents):
    for a in list(agents.values()):
        try:
            await a.stop()
        except Exception:
            pass


def test_9node_converges_through_10pct_loss(run, tmp_path):
    async def main():
        from corrosion_tpu.agent.testing import wait_for

        n = 9
        ctrl = FaultController(FaultPlan(seed=3, drop=0.10))
        agents = await _boot_cluster(n, tmp_path, ctrl)
        try:
            for i in range(6):
                agents[f"n{i % n}"].execute_transaction([
                    ("INSERT INTO tests (id, text) VALUES (?, ?)",
                     (i, f"lossy{i}"))
                ])
            await wait_for(
                lambda: all(
                    len(_table(a)) == 6 for a in agents.values()
                ) and len({
                    tuple(_table(a)) for a in agents.values()
                }) == 1,
                timeout=60,
            )
            # faults actually fired (10% of the gossip volume)
            assert ctrl.injected["drop"] > 0
        finally:
            await _stop_all(agents)

    run(main())


def test_9node_partition_heal_cycle_converges(run, tmp_path):
    async def main():
        from corrosion_tpu.agent.testing import wait_for

        n = 9
        ctrl = FaultController(FaultPlan(seed=5, partition_blocks=3))
        agents = await _boot_cluster(
            n, tmp_path, ctrl, suspect_timeout=30.0
        )
        try:
            ctrl.split()
            # one write per block while split
            for i, writer in enumerate(("n0", "n3", "n6")):
                agents[writer].execute_transaction([
                    ("INSERT INTO tests (id, text) VALUES (?, ?)",
                     (i, f"block{i}"))
                ])
            # in-block convergence: every node gets ITS block's write
            own = {
                f"n{j}": [(j // 3, f"block{j // 3}")] for j in range(n)
            }
            try:
                await wait_for(
                    lambda: all(
                        own[name][0] in _table(a)
                        for name, a in agents.items()
                    ),
                    timeout=45,
                )
            except TimeoutError:
                state = {
                    name: _table(a) for name, a in agents.items()
                }
                raise AssertionError(
                    f"in-block delivery stalled: tables={state} "
                    f"injected={ctrl.injected}"
                )
            # isolation: while the partition holds, no node has any
            # OTHER block's write
            for name, a in agents.items():
                assert _table(a) == own[name], (
                    f"partition leaked: {name} has {_table(a)}, "
                    f"expected {own[name]}"
                )
            assert ctrl.injected["partition"] > 0
            ctrl.heal()
            want = [(0, "block0"), (1, "block1"), (2, "block2")]
            await wait_for(
                lambda: all(_table(a) == want for a in agents.values()),
                timeout=60,
            )
        finally:
            await _stop_all(agents)

    run(main())


def test_9node_crash_restart_catches_up_via_anti_entropy(run, tmp_path):
    async def main():
        from corrosion_tpu.agent.testing import wait_for
        from corrosion_tpu.devcluster import run_crash_schedule

        n = 9
        ctrl = FaultController(FaultPlan(
            seed=11,
            crashes=(CrashEvent("n8", at=0.0, restart_at=0.8),),
        ))
        agents = await _boot_cluster(n, tmp_path, ctrl)
        try:
            victim_actor = agents["n8"].actor_id
            ctrl.restart_clock()
            crash_task = asyncio.ensure_future(run_crash_schedule(ctrl))
            # wait until the victim is actually down before writing so
            # the writes genuinely miss it
            await wait_for(
                lambda: any(e == "crash" for _, e, _n in ctrl.crash_log),
                timeout=10,
            )
            for i in range(8):
                agents[f"n{i % (n - 1)}"].execute_transaction([
                    ("INSERT INTO tests (id, text) VALUES (?, ?)",
                     (i, f"missed{i}"))
                ])
            await asyncio.wait_for(crash_task, timeout=20)
            reborn = ctrl.agents["n8"]
            assert reborn.actor_id == victim_actor  # resume, not re-seed
            await wait_for(
                lambda: len(_table(reborn)) == 8
                and _table(reborn) == _table(ctrl.agents["n0"]),
                timeout=60,
            )
        finally:
            await _stop_all(ctrl.agents)

    run(main())


def test_flush_round_does_not_stall_on_dead_peer(run, tmp_path):
    """With one peer crashed, a broadcast flush round stays bounded:
    breaker + bounded redial keep the live peers converging fast, and
    the dead peer's breaker opens (quarantining it from fanout)."""
    async def main():
        from corrosion_tpu.agent.testing import wait_for

        n = 5
        ctrl = FaultController(FaultPlan(seed=2))
        agents = await _boot_cluster(
            n, tmp_path, ctrl,
            breaker_threshold=2,
            # long suspicion: SWIM must NOT down-mark the corpse for
            # us — the transport layer alone has to stay bounded
            suspect_timeout=60.0,
            connect_timeout=0.3,
        )
        try:
            dead_addr = tuple(agents["n4"].gossip_addr)
            await agents["n4"].stop(graceful=False)
            live = [agents[f"n{i}"] for i in range(4)]
            t0 = time.perf_counter()
            for i in range(10):
                live[i % 4].execute_transaction([
                    ("INSERT INTO tests (id, text) VALUES (?, ?)",
                     (i, f"bounded{i}"))
                ])
            await wait_for(
                lambda: all(len(_table(a)) == 10 for a in live)
                and len({tuple(_table(a)) for a in live}) == 1,
                timeout=15,
            )
            elapsed = time.perf_counter() - t0
            # well under the suite budget: the corpse cost at most a
            # few connect timeouts before its breaker opened
            assert elapsed < 15.0
            opened = sum(
                a.transport.stats[dead_addr].breaker_opens
                for a in live
                if dead_addr in a.transport.stats
            )
            assert opened >= 1
            # ConnStats surfaced the degraded-mode accounting
            assert any(
                a.transport.stats[dead_addr].failures > 0
                for a in live
                if dead_addr in a.transport.stats
            )
        finally:
            await _stop_all(agents)

    run(main())


def test_admin_cluster_members_exposes_transport_and_faults(run, tmp_path):
    async def main():
        from corrosion_tpu.agent.admin import _handle
        from corrosion_tpu.agent.testing import wait_for

        n = 3
        ctrl = FaultController(FaultPlan(seed=1, drop=0.05))
        agents = await _boot_cluster(n, tmp_path, ctrl)
        try:
            agents["n0"].execute_transaction([
                ("INSERT INTO tests (id, text) VALUES (1, 'x')",)
            ])
            await wait_for(
                lambda: all(len(_table(a)) == 1 for a in agents.values()),
                timeout=20,
            )
            a = agents["n0"]
            members = _handle(a, {"cmd": "cluster_members"})["ok"]
            assert len(members) == n - 1
            for m in members:
                assert "breaker" in m and "quarantined" in m
                if m["transport"] is not None:
                    for k in ("faults_dropped", "redials",
                              "breaker_opens"):
                        assert k in m["transport"]
            faults = _handle(a, {"cmd": "faults"})["ok"]
            assert faults["drop"] == 0.05
            assert faults["nodes"] == n
            assert faults["decisions"] > 0
            ts = _handle(a, {"cmd": "transport_stats"})["ok"]
            assert isinstance(ts, dict)
        finally:
            await _stop_all(agents)

    run(main())


# ---------------------------------------------------------------------------
# the N≈32 soak (bench.py --chaos writes CHAOS_N32.json from this path)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_n32(run, tmp_path):
    async def main():
        from corrosion_tpu.sim.chaos import run_chaos

        out = tmp_path / "CHAOS_N32.json"
        result = await run_chaos(
            n=32, out_path=str(out), base_dir=str(tmp_path / "cluster")
        )
        assert result["diff"]["both_converged"]
        assert out.exists()

    run(main())
