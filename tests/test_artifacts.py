"""Committed-artifact schema lint (tier-1).

The bench JSONs committed at the repo root are load-bearing: ROADMAP
claims, docs tables, and the overhead/trajectory gates all cite them.
A refactor that silently changes an artifact's shape (or commits a
failing one) should fail fast here, not months later when someone
re-reads the numbers.  The schemas are deliberately MINIMAL — required
keys and types, plus the health invariants each artifact asserts
in-record — so benches stay free to grow new fields.
"""

from __future__ import annotations

import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

NUM = (int, float)


def _load(name: str) -> dict:
    p = REPO / name
    assert p.exists(), f"committed artifact {name} is missing"
    with open(p) as f:
        return json.load(f)


def _check(doc, schema, path="$"):
    """Minimal structural validation: dict schema = required keys with
    nested schemas; a type/tuple-of-types = isinstance; a callable =
    predicate (must return True)."""
    if isinstance(schema, dict):
        assert isinstance(doc, dict), f"{path}: expected object"
        for key, sub in schema.items():
            assert key in doc, f"{path}: missing required key {key!r}"
            _check(doc[key], sub, f"{path}.{key}")
    elif isinstance(schema, (type, tuple)):
        assert isinstance(doc, schema), (
            f"{path}: expected {schema}, got {type(doc).__name__}"
        )
    else:  # predicate
        assert schema(doc), f"{path}: predicate failed on {doc!r}"


def _gate_passed(g):
    # overhead gates either ran (pass True) or were skipped at smoke
    # scale — a committed artifact must never carry pass=False
    return isinstance(g, dict) and g.get("pass") is not False


def test_chaos_artifact_schema():
    doc = _load("CHAOS_N32.json")
    _check(doc, {
        "n_nodes": int,
        "fault_family": dict,
        "sim": {"converged_frac": NUM, "msgs_per_node": NUM},
        "agents": {"converged_frac": lambda v: v == 1.0},
        "diff": dict,
    })
    assert "error" not in doc


def test_obs_artifact_schema():
    doc = _load("OBS_N32.json")
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "value": NUM,
        "tolerance": NUM,
        "within_tolerance": lambda v: v is True,
        "agents": {
            "ground_truth": {"p99_s": NUM},
            "telemetry": {"lag": {"p99_s": NUM}},
        },
        "sim": dict,
        "diff": dict,
    })
    assert "error" not in doc


def test_scenarios_artifact_schema():
    doc = _load("SCENARIOS_N32.json")
    # live-socket evidence must stay live: a `bench.py --scenarios
    # --virtual-time --n 32` run writes the SAME filename, and the
    # virtual record is deliberately shaped like the live one — only
    # the runtime marker tells them apart
    assert doc.get("runtime") != "virtual", (
        "SCENARIOS_N32.json was overwritten by a virtual-time run"
    )
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "families": list,
        "all_cells_converged": lambda v: v is True,
        "no_divergence_all_cells": lambda v: v is True,
        "all_gates_passed": lambda v: v is True,
        "cells": dict,
    })
    assert set(doc["families"]) == set(doc["cells"])
    for family, cell in doc["cells"].items():
        _check(cell, {
            "agents": {
                "gates": dict,
                "no_divergence": {"ok": lambda v: v is True},
                # the flight-recorder attachment: every cell ships its
                # own post-mortem (events + snapshots + coverage)
                "timeline": {
                    "snapshots": lambda v: isinstance(v, int) and v > 0,
                    "event_counts": dict,
                    "events": list,
                    "coverage": {"expected": int, "offsets_s": list},
                },
                "passed": lambda v: v is True,
            },
            "diff": dict,
        }, f"$.cells.{family}")


def test_timeline_artifact_schema():
    doc = _load("TIMELINE_N32.json")
    assert doc.get("runtime") != "virtual", (
        "TIMELINE_N32.json was overwritten by a virtual-time run"
    )
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "agents": {
            "converged": lambda v: v is True,
            "coverage": {
                "expected": int,
                "offsets_s": list,
                "t_at_coverage": dict,
            },
            "timeline": {
                "snapshots": lambda v: isinstance(v, int) and v > 0,
                "event_counts": dict,
                "events": list,
            },
        },
        "sim": {
            "times_s": list,
            "coverage": list,
            "t_at_coverage": dict,
        },
        "trajectory": {
            "gates": dict,
            "plateau_tolerance": NUM,
            "recovery_budget_s": NUM,
        },
        "all_gates_passed": lambda v: v is True,
        "overhead_gate": _gate_passed,
    })
    assert all(doc["trajectory"]["gates"].values())
    assert "error" not in doc
    # the overhead A/B actually ran at the headline shape
    assert doc["overhead_gate"]["pass"] is True
    assert doc["overhead_gate"]["ratio"] >= 0.95


@pytest.mark.parametrize("name,value_floor", [
    ("APPLY_BENCH.json", 4.0),
    ("SYNC_BENCH.json", 3.0),
    ("WRITE_BENCH.json", 2.5),
])
def test_perf_bench_artifact_schemas(name, value_floor):
    doc = _load(name)
    _check(doc, {
        "metric": str,
        "value": NUM,
        "unit": str,
        "conditions": str,
        # APPLY/WRITE commit a point list; SYNC a per-mode dict
        "points": lambda v: isinstance(v, (list, dict)) and len(v) > 0,
    })
    assert "error" not in doc
    # the committed headline must actually clear its own gate
    assert doc["value"] >= value_floor, (
        f"{name}: committed headline {doc['value']} under its "
        f"{value_floor}x gate"
    )
    if "overhead_gate" in doc:
        assert _gate_passed(doc["overhead_gate"])
    if name == "APPLY_BENCH.json":
        # signed-attribution ingest overhead: the committed paired A/B
        # ran at the headline shape and held the ≥0.95 median ratio
        gate = doc["sig_overhead_gate"]
        assert gate["pass"] is True
        assert gate["ratio"] >= 0.95
        # columnar merge kernel (docs/crdts.md): the committed off/on
        # paired A/B held its ≥0.90 floor WITH in-bench state parity,
        # the event-loop stall gate held its 50 ms budget, and every
        # point's per-change/batched state digests matched
        kab = doc["kernel_ab"]
        assert kab["pass"] is True
        assert kab["parity"] is True
        assert kab["ratio"] >= 0.90
        sg = doc["stall_gate"]
        assert sg["pass"] is True
        assert sg["max_stall_ms"] <= sg["budget_ms"]
        for p in doc["points"]:
            assert p["parity"] is True, p
        # the headline batched arm actually ran the columnar kernel
        headline = next(
            p for p in doc["points"]
            if p["mode"] == "cold"
            and p["n_changes"] == max(
                q["n_changes"] for q in doc["points"]
            )
        )
        assert headline["kernel"] == "columnar"
        # device-resident apply (docs/crdts.md "Device-resident
        # apply"): the committed steady-state hot-cache arm beat the
        # committed columnar cold headline with three-arm state-digest
        # parity and a majority cache-hit rate; flood is recorded as
        # the honest cold-cache bound, not gated
        da = doc["device_arm"]
        assert da["pass"] is True
        assert da["parity"] is True
        assert da["n_changes"] == headline["n_changes"]
        steady = da["scenarios"]["steady"]
        assert steady["parity"] is True
        assert steady["speedup"] > da["floor"]
        assert steady["speedup"] > value_floor
        cache = steady["cache"]
        for key in (
            "corro_apply_cache_hits_total",
            "corro_apply_cache_misses_total",
            "corro_apply_cache_evictions_total",
            "corro_apply_cache_invalidations_total",
        ):
            assert key in cache, key
        assert cache["hit_rate"] > 0.5
        assert da["scenarios"]["flood"]["parity"] is True


def test_subs_bench_artifact_schema():
    """The subscription fan-out artifact (bench.py --subs): the
    committed 100k-sub/10k-change headline must clear its >= 3x floor
    WITH in-bench columnar/oracle verdict parity, the swarm's stall /
    staleness / converged-parity gates must all be green with the
    flight-recorder timeline attached, and the committed subs-off/on
    paired A/B must hold the >= 0.95 write-path ratio."""
    doc = _load("SUBS_BENCH.json")
    _check(doc, {
        "metric": lambda v: v == "subs_matcher_columnar_speedup",
        "value": NUM,
        "unit": lambda v: v == "x",
        "conditions": str,
        "headline": {
            "n_subs": lambda v: v >= 100_000,
            "n_changes": lambda v: v >= 10_000,
        },
        "points": lambda v: isinstance(v, list) and len(v) > 0,
        "parity": {
            "ok": lambda v: v is True,
            "compared_pairs": lambda v: v > 0,
            "mismatches": lambda v: v == 0,
        },
        "swarm": {
            "n_subs": lambda v: v > 0,
            "parity_ok": lambda v: v is True,
            "stall_gate": {
                "max_stall_ms": NUM,
                "budget_ms": NUM,
                "pass": lambda v: v is True,
            },
            "staleness_gate": {
                "p99_s": NUM,
                "slo_s": NUM,
                "samples": lambda v: v > 0,
                "pass": lambda v: v is True,
            },
            "counters": dict,
            "timeline": {
                "snapshots": lambda v: isinstance(v, int) and v > 0,
                "event_counts": dict,
                "events": list,
            },
        },
        "overhead_gate": {
            "ratio": NUM,
            "pairs": list,
            "pass": lambda v: v is True,
        },
    })
    assert "error" not in doc
    assert doc["value"] >= 3.0, (
        f"committed subs headline {doc['value']} under its 3x gate"
    )
    assert doc["overhead_gate"]["ratio"] >= 0.95
    # the committed swarm actually exercised the columnar fast path
    assert doc["swarm"]["counters"][
        "corro_subs_columnar_verdicts_total"] > 0


def test_frontier_bench_artifact_schema():
    """The frontier-sparse BENCH headline (bench.py --frontier): the
    exact sampler's p99 convergence + msgs/node swept through N=10M,
    every point tagged with the kernel the bitmap-budget dispatch
    selected AND the budget it was derived from, the 10M headline
    produced by the MULTI-HOST frontier kernel (delta-only cross-host
    exchange) and converged, the dense/sparse exactness gate green,
    the in-record multi-host bitwise gate green, the 100k perf gate
    green (the sparse kernel must not cost the existing scale
    anything), and one sweep point per scenario topology beyond
    uniform fanout — including the measured-RTT ring and WAN latency
    families."""
    KERNELS = {"dense", "sharded-dense", "sparse", "sharded-sparse",
               "host-sparse"}
    doc = _load("BENCH_FRONTIER.json")
    _check(doc, {
        "metric": lambda v: v == "epidemic_exact_frontier_sweep_vs_n",
        "value": NUM,
        "unit": lambda v: v == "ticks",
        "conditions": str,
        "kernel_budget": {
            "bitmap_budget_bytes": lambda v: isinstance(v, int) and v > 0,
            "source": str,
            "devices": int,
            "backend": str,
        },
        "points": lambda v: isinstance(v, list) and len(v) >= 3,
        "headline": {
            # the 10M headline can only come from the multi-host
            # frontier kernel (the dense bitmap is ~12.5 TB there, and
            # the single-host sparse run is the 1M point's job)
            "n": lambda v: v == 10_000_000,
            "ticks_p99": NUM,
            "msgs_per_node_mean": NUM,
            "msgs_per_node_p99": NUM,
            "converged_frac": lambda v: v == 1.0,
            "kernel": lambda v: v == "host-sparse",
            "n_hosts": lambda v: isinstance(v, int) and v >= 2,
            "wall_s": NUM,
        },
        "exactness_gate": {"pass": lambda v: v is True},
        "multi_host_gate": {
            "n_hosts": lambda v: isinstance(v, int) and v >= 2,
            "pass": lambda v: v is True,
        },
        "perf_gate_100k": {
            "dense_wall_s": NUM,
            "sparse_wall_s": NUM,
            "sparse_over_dense": lambda v: v <= 1.0,
            "stats_equal": lambda v: v is True,
            "pass": lambda v: v is True,
        },
        "topologies": dict,
    })
    assert "error" not in doc
    # headline floors: the committed 10M point converged with the
    # protocol's own message bound (budget*fanout broadcast + sync
    # session accounting), in sane epidemic depth
    hl = doc["headline"]
    assert hl["msgs_per_node_mean"] < 64
    assert 8 <= hl["ticks_p99"] <= 64
    # the in-record multi-host witness covered the headline shape AND
    # both new topology families, bitwise
    mh = doc["multi_host_gate"]
    for fam in ("headline", "measured_ring", "wan_latency"):
        assert mh[fam]["bitwise_equal"] is True, fam
    # every successful point carries a recognized kernel tag and the
    # budget its dispatch was derived from, and the sweep exercised
    # more than one representation
    ok_points = [p for p in doc["points"] if "error" not in p]
    tags = {p["kernel"] for p in ok_points}
    assert tags <= KERNELS and len(tags) >= 2, tags
    for p in ok_points:
        assert p["bitmap_budget_bytes"] > 0, p
        assert isinstance(p["budget_source"], str), p
    # one committed sweep point per scenario topology, converged —
    # including the measured-RTT ring (captured tier weights) and the
    # WAN latency-queue family (delayed delivery, zero extra loss)
    for topo in ("het_ring", "wan_two_region", "measured_ring",
                 "wan_latency"):
        cell = doc["topologies"][topo]
        assert "error" not in cell, cell
        assert cell["converged_frac"] == 1.0
        assert cell["kernel"] in KERNELS
    assert sum(doc["topologies"]["measured_ring"]["rtt_tier_weights"]) > 0
    assert doc["topologies"]["wan_latency"]["wan_latency_ticks"] >= 1
    assert doc["topologies"]["wan_latency"]["wan_cross_loss"] == 0.0
    # the wan family converges THROUGH sync; het_ring's slow arc may
    # not beat uniform's depth, but both stay within protocol bounds
    assert doc["topologies"]["het_ring"]["msgs_per_node_mean"] < 64


def test_boot_bench_artifact_schema():
    """The bootstrap-recovery artifact (bench.py --boot): a fresh
    node's change-by-change catch-up vs snapshot install + tail sync
    over a 10k-version history — the committed headline must clear the
    >=5x floor, the snapshot arm must have genuinely installed, and
    the flight-recorder trajectory must carry the install event within
    the in-record recovery budget."""
    doc = _load("BOOT_BENCH.json")
    _check(doc, {
        "metric": lambda v: v == "boot_recovery_speedup",
        "value": NUM,
        "unit": lambda v: v == "x",
        "conditions": str,
        "n_versions": lambda v: v >= 10_000,
        "recovery_budget_s": NUM,
        "points": {
            "changes": {
                "recovery_s": NUM,
                "converged": lambda v: v is True,
                # the oracle arm must never have taken the shortcut
                "snapshot_installs": lambda v: v == 0,
            },
            "snapshot": {
                "recovery_s": NUM,
                "converged": lambda v: v is True,
                "snapshot_installs": lambda v: v >= 1,
                "snapshot_served_bytes": lambda v: v > 0,
                "trajectory": lambda v: isinstance(v, list) and any(
                    e["kind"] == "snap_install" for e in v
                ),
            },
        },
        "gates": {
            "both_converged": lambda v: v is True,
            "installed_via_snapshot": lambda v: v is True,
            "trajectory_has_install": lambda v: v is True,
            "within_budget": lambda v: v is True,
        },
    })
    assert "error" not in doc
    assert doc["value"] >= 5.0, (
        f"committed boot headline {doc['value']} under its 5x gate"
    )
    assert (doc["points"]["snapshot"]["recovery_s"]
            <= doc["recovery_budget_s"])
    # the trajectory's install event lands inside the measured wall
    install = [
        e for e in doc["points"]["snapshot"]["trajectory"]
        if e["kind"] == "snap_install"
    ][0]
    assert 0 <= install["t_s"] <= doc["points"]["snapshot"]["recovery_s"]


def test_virtual_scenarios_n512_artifact_schema():
    """The virtual-time campaign artifact (bench.py --scenarios
    --virtual-time --n 512): the full matrix PLUS the scale-only cells
    (restart storm, hostile-fraction sweeps, crash-composed compounds),
    every gate green, every cell carrying its no-divergence verdict,
    timeline attachment and end-state checksum — and the whole
    campaign's wall cost recorded in-record (the point of virtual
    time: N=512 in seconds, not hours)."""
    doc = _load("SCENARIOS_N512.json")
    _check(doc, {
        "n_nodes": lambda v: v == 512,
        "metric": str,
        "runtime": lambda v: v == "virtual",
        "families": list,
        "all_cells_converged": lambda v: v is True,
        "no_divergence_all_cells": lambda v: v is True,
        "all_gates_passed": lambda v: v is True,
        "wall_s_total": NUM,
        "cells": dict,
    })
    assert set(doc["families"]) == set(doc["cells"])
    # the scale-only families actually ran at scale — including the
    # signed-attribution and Byzantine-sync-serve cells
    for fam in ("restart_storm", "hostile_sweep_8", "hostile_sweep_32",
                "equiv_during_heal", "skew_during_restart",
                "framing_relay", "signed_equivocator",
                "byz_sync_server", "hostile_sweep_32_signed",
                "restart_storm_snapshot", "byz_snapshot_server",
                "crash_mid_install"):
        assert fam in doc["cells"], f"scale family {fam} missing"
    for family, cell in doc["cells"].items():
        _check(cell, {
            "agents": {
                "runtime": lambda v: v == "virtual",
                "gates": dict,
                "no_divergence": {"ok": lambda v: v is True},
                "state_checksum": str,
                "virtual_to_converge_s": NUM,
                "wall_s": NUM,
                "timeline": {
                    "snapshots": lambda v: isinstance(v, int) and v > 0,
                    "event_counts": dict,
                    "events": list,
                    "coverage": {"expected": int, "offsets_s": list},
                },
                "passed": lambda v: v is True,
            },
            "diff": dict,
        }, f"$.cells.{family}")
    # the asym_partition prediction is now the DIRECTED kernel: no
    # partition residual, oneway matrix recorded
    asym_sim = doc["cells"]["asym_partition"]["sim"]
    assert asym_sim is not None
    assert asym_sim.get("oneway_blocks") == [[0, 1]]
    assert "residual" not in asym_sim
    # the framing_relay headline NEGATIVE control, in-record: the
    # tampering relay was blamed on every victim while the framed
    # honest origin was quarantined on ZERO nodes
    framing = doc["cells"]["framing_relay"]["agents"]
    _check(framing["detail"]["framing"], {
        "origin_quarantined_nodes": lambda v: v == 0,
        "victims": lambda v: isinstance(v, int) and v >= 500,
        "sig_fail_verifications": lambda v: v >= 1,
    }, "$.cells.framing_relay.detail.framing")
    assert framing["gates"]["origin_never_quarantined"] is True
    assert framing["gates"]["relay_blamed_everywhere"] is True
    # the permanent signed verdict survived its victim's restart
    se_gates = doc["cells"]["signed_equivocator"]["agents"]["gates"]
    assert se_gates["signed_verdict_permanent"] is True
    assert se_gates["proof_survived_restart"] is True
    # every Byzantine sync-serve defense actually fired
    byz_gates = doc["cells"]["byz_sync_server"]["agents"]["gates"]
    for reason in ("advertised_range", "need_cap", "frame_garbage",
                   "deadline"):
        assert byz_gates[f"rejected_{reason}"] is True, reason
    # snapshot-bootstrap cells (docs/sync.md): reborn nodes installed
    # via snapshot; a hostile snapshot server was contained on the
    # digest gate with ZERO installs and zero tampered rows
    # cluster-wide; every mid-install death recovered to convergence
    storm = doc["cells"]["restart_storm_snapshot"]["agents"]
    assert storm["gates"]["reborn_installed_via_snapshot"] is True
    assert storm["gates"]["snapshots_served"] is True
    assert storm["detail"]["snapshot"]["installs_ok"] >= 1
    sbyz = doc["cells"]["byz_snapshot_server"]["agents"]
    assert sbyz["gates"]["rejected_snap_digest"] is True
    assert sbyz["gates"]["hostile_never_installed"] is True
    assert sbyz["gates"]["zero_tampered_rows"] is True
    cmi = doc["cells"]["crash_mid_install"]["agents"]
    assert cmi["gates"]["snap_crashes_fired"] is True
    assert cmi["gates"]["recovery_retry_seen"] is True
    assert cmi["gates"]["recovery_finalized_seen"] is True
    assert cmi["gates"]["retries_installed"] is True
    assert "error" not in doc


def test_virtual_timeline_n512_artifact_schema():
    """The virtual trajectory artifact (bench.py --timeline
    --virtual-time --n 512): the N=512 partition-heal coverage
    trajectory gated against the kernel's per-tick curve, plus the
    N=32 virtual-vs-real parity cell within its named tolerances."""
    doc = _load("TIMELINE_N512.json")
    _check(doc, {
        "n_nodes": lambda v: v == 512,
        "metric": str,
        "runtime": lambda v: v == "virtual",
        "agents": {
            "runtime": lambda v: v == "virtual-agents",
            "converged": lambda v: v is True,
            "campaign_wall_s": NUM,
            "coverage": {
                "expected": int,
                "offsets_s": list,
                "t_at_coverage": dict,
            },
            "timeline": {
                "snapshots": lambda v: isinstance(v, int) and v > 0,
                "event_counts": dict,
                "events": list,
            },
        },
        "sim": {
            "times_s": list,
            "coverage": list,
            "t_at_coverage": dict,
        },
        "trajectory": {
            "gates": dict,
            "plateau_tolerance": NUM,
            "recovery_budget_s": NUM,
        },
        "parity_n32": {
            "n_nodes": lambda v: v == 32,
            "gates": dict,
            "passed": lambda v: v is True,
            "plateau_tolerance": NUM,
            "recovery_factor": NUM,
            "msgs_factor": NUM,
        },
        "all_gates_passed": lambda v: v is True,
    })
    assert all(doc["trajectory"]["gates"].values())
    assert all(doc["parity_n32"]["gates"].values())
    assert "error" not in doc


def test_virtual_campaign_wall_budget():
    """The acceptance bound the refactor exists for: the committed
    N=512 five-family matrix + the partition-heal trajectory cell
    completed in < 120 s wall COMBINED on the host that generated them
    (in-record walls; the scale-only cells — sweeps, storms — ride in
    the same artifact with their own cost on top, recorded as
    wall_s_total)."""
    scen = _load("SCENARIOS_N512.json")
    tl = _load("TIMELINE_N512.json")
    total = scen["wall_s_matrix"] + tl["agents"]["campaign_wall_s"]
    assert total < 120.0, (
        f"virtual matrix+trajectory took {total:.1f}s wall combined"
    )
    assert scen["wall_s_total"] >= scen["wall_s_matrix"]


def test_topology_measured_artifact_schema():
    """The captured measured-RTT topology (bench.py --capture-topology
    / the agent admin `rtt dump` export): a real multi-tier Members
    RTT distribution from the deterministic virtual-cluster campaign,
    in exactly the shape `--frontier --topology measured_ring` and
    ``HeadlineExactConfig(rtt_tier_weights=...)`` consume."""
    doc = _load("TOPOLOGY_MEASURED.json")
    _check(doc, {
        "topology": lambda v: v == "measured_ring",
        "tier_edges_ms": lambda v: isinstance(v, list) and len(v) >= 2
        and all(b > a for a, b in zip(v, v[1:])),
        "rtt_tiers": lambda v: isinstance(v, int) and v >= 2,
        "weights": lambda v: isinstance(v, list) and sum(v) > 0
        and all(isinstance(w, int) and w >= 0 for w in v),
        "members_sampled": lambda v: isinstance(v, int) and v > 0,
        "members_unsampled": int,
        "nodes": lambda v: isinstance(v, list) and len(v) >= 2,
        "capture": {"campaign": str, "n": int, "seed": int},
    })
    # genuinely heterogeneous: the distribution spans >= 2 tiers
    assert sum(1 for w in doc["weights"] if w > 0) >= 2
    assert len(doc["weights"]) == doc["rtt_tiers"]
