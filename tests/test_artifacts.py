"""Committed-artifact schema lint (tier-1).

The bench JSONs committed at the repo root are load-bearing: ROADMAP
claims, docs tables, and the overhead/trajectory gates all cite them.
A refactor that silently changes an artifact's shape (or commits a
failing one) should fail fast here, not months later when someone
re-reads the numbers.  The schemas are deliberately MINIMAL — required
keys and types, plus the health invariants each artifact asserts
in-record — so benches stay free to grow new fields.
"""

from __future__ import annotations

import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

NUM = (int, float)


def _load(name: str) -> dict:
    p = REPO / name
    assert p.exists(), f"committed artifact {name} is missing"
    with open(p) as f:
        return json.load(f)


def _check(doc, schema, path="$"):
    """Minimal structural validation: dict schema = required keys with
    nested schemas; a type/tuple-of-types = isinstance; a callable =
    predicate (must return True)."""
    if isinstance(schema, dict):
        assert isinstance(doc, dict), f"{path}: expected object"
        for key, sub in schema.items():
            assert key in doc, f"{path}: missing required key {key!r}"
            _check(doc[key], sub, f"{path}.{key}")
    elif isinstance(schema, (type, tuple)):
        assert isinstance(doc, schema), (
            f"{path}: expected {schema}, got {type(doc).__name__}"
        )
    else:  # predicate
        assert schema(doc), f"{path}: predicate failed on {doc!r}"


def _gate_passed(g):
    # overhead gates either ran (pass True) or were skipped at smoke
    # scale — a committed artifact must never carry pass=False
    return isinstance(g, dict) and g.get("pass") is not False


def test_chaos_artifact_schema():
    doc = _load("CHAOS_N32.json")
    _check(doc, {
        "n_nodes": int,
        "fault_family": dict,
        "sim": {"converged_frac": NUM, "msgs_per_node": NUM},
        "agents": {"converged_frac": lambda v: v == 1.0},
        "diff": dict,
    })
    assert "error" not in doc


def test_obs_artifact_schema():
    doc = _load("OBS_N32.json")
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "value": NUM,
        "tolerance": NUM,
        "within_tolerance": lambda v: v is True,
        "agents": {
            "ground_truth": {"p99_s": NUM},
            "telemetry": {"lag": {"p99_s": NUM}},
        },
        "sim": dict,
        "diff": dict,
    })
    assert "error" not in doc


def test_scenarios_artifact_schema():
    doc = _load("SCENARIOS_N32.json")
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "families": list,
        "all_cells_converged": lambda v: v is True,
        "no_divergence_all_cells": lambda v: v is True,
        "all_gates_passed": lambda v: v is True,
        "cells": dict,
    })
    assert set(doc["families"]) == set(doc["cells"])
    for family, cell in doc["cells"].items():
        _check(cell, {
            "agents": {
                "gates": dict,
                "no_divergence": {"ok": lambda v: v is True},
                # the flight-recorder attachment: every cell ships its
                # own post-mortem (events + snapshots + coverage)
                "timeline": {
                    "snapshots": lambda v: isinstance(v, int) and v > 0,
                    "event_counts": dict,
                    "events": list,
                    "coverage": {"expected": int, "offsets_s": list},
                },
                "passed": lambda v: v is True,
            },
            "diff": dict,
        }, f"$.cells.{family}")


def test_timeline_artifact_schema():
    doc = _load("TIMELINE_N32.json")
    _check(doc, {
        "n_nodes": int,
        "metric": str,
        "agents": {
            "converged": lambda v: v is True,
            "coverage": {
                "expected": int,
                "offsets_s": list,
                "t_at_coverage": dict,
            },
            "timeline": {
                "snapshots": lambda v: isinstance(v, int) and v > 0,
                "event_counts": dict,
                "events": list,
            },
        },
        "sim": {
            "times_s": list,
            "coverage": list,
            "t_at_coverage": dict,
        },
        "trajectory": {
            "gates": dict,
            "plateau_tolerance": NUM,
            "recovery_budget_s": NUM,
        },
        "all_gates_passed": lambda v: v is True,
        "overhead_gate": _gate_passed,
    })
    assert all(doc["trajectory"]["gates"].values())
    assert "error" not in doc
    # the overhead A/B actually ran at the headline shape
    assert doc["overhead_gate"]["pass"] is True
    assert doc["overhead_gate"]["ratio"] >= 0.95


@pytest.mark.parametrize("name,value_floor", [
    ("APPLY_BENCH.json", 3.0),
    ("SYNC_BENCH.json", 3.0),
    ("WRITE_BENCH.json", 2.5),
])
def test_perf_bench_artifact_schemas(name, value_floor):
    doc = _load(name)
    _check(doc, {
        "metric": str,
        "value": NUM,
        "unit": str,
        "conditions": str,
        # APPLY/WRITE commit a point list; SYNC a per-mode dict
        "points": lambda v: isinstance(v, (list, dict)) and len(v) > 0,
    })
    assert "error" not in doc
    # the committed headline must actually clear its own gate
    assert doc["value"] >= value_floor, (
        f"{name}: committed headline {doc['value']} under its "
        f"{value_floor}x gate"
    )
    if "overhead_gate" in doc:
        assert _gate_passed(doc["overhead_gate"])
