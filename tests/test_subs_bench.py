"""Subscription fan-out benchmark harness checks.

Tier-1 runs the full ``bench.py --subs`` machinery at 500 subs over a
500-change burst (a smoke: in-bench columnar/oracle verdict parity
must hold, the swarm's stall/staleness/converged-parity gates must
pass, the columnar path must actually fire); the 100k-sub/10k-change
headline gates (>= 3x verdict-pair throughput, the subs-off/on
write-path A/B >= 0.95) run in the @slow tier.
"""

import pytest

from bench import run_subs_bench


def test_subs_bench_smoke_500():
    out = run_subs_bench(n_subs=500, n_changes=500, swarm_subs=48,
                         swarm_writes=200, ab=False, out_path=None)
    assert "error" not in out, out.get("error")
    # a verdict mismatch voids the headline — the smoke pins that the
    # comparison ran and held
    assert out["value"] is not None and out["value"] > 0
    assert out["parity"]["ok"] is True
    assert out["parity"]["compared_pairs"] > 0
    (p,) = out["points"]
    # both arms delivered the same verdict pairs over the same burst
    assert p["columnar"]["verdict_pairs"] > 0
    assert p["oracle"]["verdict_pairs"] > 0
    # the swarm's three gates all held at smoke scale
    sw = out["swarm"]
    assert sw["stall_gate"]["pass"] is True
    assert sw["staleness_gate"]["pass"] is True
    assert sw["parity_ok"] is True, sw["mismatched_subs"]
    # the live plane actually exercised the columnar fast path and the
    # widened detectors (a silently-degraded plane would pass parity
    # vacuously)
    assert sw["counters"]["corro_subs_columnar_verdicts_total"] > 0
    assert sw["counters"]["corro_subs_bounded_refresh_total"] > 0
    # flight-recorder timeline attached
    assert sw["timeline"]["snapshots"] > 0
    # the A/B is deliberately skipped at smoke scale
    assert out["overhead_gate"]["pass"] is None


@pytest.mark.slow
def test_subs_bench_headline_100k():
    out = run_subs_bench(out_path=None)
    assert "error" not in out, out.get("error")
    # acceptance gates: >= 3x sharded-columnar verdict throughput at
    # the 100k-sub/10k-change headline with in-bench parity, swarm
    # staleness SLO + <= 50 ms loop stall, subs plane write-path cost
    # within 5% in the paired off/on A/B
    assert out["value"] >= 3.0, out
    assert out["parity"]["ok"] is True
    assert out["swarm"]["stall_gate"]["pass"] is True
    assert out["swarm"]["staleness_gate"]["pass"] is True
    assert out["swarm"]["parity_ok"] is True
    assert out["overhead_gate"]["pass"] is True
    assert out["overhead_gate"]["ratio"] >= 0.95
