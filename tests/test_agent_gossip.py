"""Multi-agent integration tests over real loopback gossip.

Mirrors the reference's primary strategy (SURVEY.md §4): boot complete
agents in-process, wire them via bootstrap, and exercise real network
paths — no mocks.
"""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for


def addr_str(agent):
    h, p = agent.gossip_addr
    return f"{h}:{p}"


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_two_agents_meet_and_gossip(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            assert a.members.get(b.actor_id) is not None
            assert b.members.get(a.actor_id) is not None

            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "hello"]]]
            )
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT text FROM tests WHERE id=1"
                ).fetchone()
            )
            row = b.storage.conn.execute(
                "SELECT text FROM tests WHERE id=1"
            ).fetchone()
            assert row == ("hello",)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_three_agents_write_everywhere(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        c = await launch_test_agent(bootstrap=[addr_str(a)])
        agents = [a, b, c]
        try:
            await wait_for(
                lambda: all(len(x.members.alive()) == 2 for x in agents)
            )
            for i, agent in enumerate(agents):
                agent.execute_transaction(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"from-{i}"]]]
                )

            def all_have_all():
                for x in agents:
                    rows = x.storage.conn.execute(
                        "SELECT id, text FROM tests ORDER BY id"
                    ).fetchall()
                    if rows != [(0, "from-0"), (1, "from-1"), (2, "from-2")]:
                        return False
                return True

            await wait_for(all_have_all)
        finally:
            for x in agents:
                await x.stop()

    run(main())


def test_sync_catches_up_late_joiner(run):
    async def main():
        a = await launch_test_agent()
        try:
            for i in range(10):
                a.execute_transaction(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)", [i, f"t{i}"]]]
                )
            # b joins AFTER the writes: only anti-entropy can catch it up
            b = await launch_test_agent(bootstrap=[addr_str(a)])
            try:
                await wait_for(
                    lambda: b.storage.conn.execute(
                        "SELECT COUNT(*) FROM tests"
                    ).fetchone()[0] == 10,
                    timeout=15.0,
                )
                # bookkeeping caught up too
                bv = b.bookie.for_actor(a.actor_id)
                assert bv.last() == 10
                assert bv.needed_spans() == []
            finally:
                await b.stop()
        finally:
            await a.stop()

    run(main())


def test_large_tx_chunked_and_reassembled(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # one transaction big enough to split into multiple 8KiB chunks
            stmts = [
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [i, "x" * 512]]
                for i in range(200)
            ]
            out = a.execute_transaction(stmts)
            assert out["version"] == 1
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT COUNT(*) FROM tests"
                ).fetchone()[0] == 200,
                timeout=15.0,
            )
            bv = b.bookie.for_actor(a.actor_id)
            assert bv.partials == {}
            assert bv.contains_version(1)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_deletes_propagate(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'gone soon')"]]
            )
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT COUNT(*) FROM tests"
                ).fetchone()[0] == 1
            )
            a.execute_transaction([["DELETE FROM tests WHERE id=1"]])
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT COUNT(*) FROM tests"
                ).fetchone()[0] == 0
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_failure_detection_and_member_state(run):
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # crash (no graceful leave): only probe failure detects it
            await b.stop(graceful=False)
            # a must eventually mark b suspect then down
            await wait_for(
                lambda: (
                    (m := a.members.get(b.actor_id)) is not None
                    and m.state.value == "down"
                ),
                timeout=15.0,
            )
        finally:
            await a.stop()

    run(main())


def test_complementary_partials_complete_each_other(run):
    """Two peers holding complementary chunks of a version can complete
    each other through sync even after the origin is gone."""
    from corrosion_tpu.types import ChangeSource, ChangeV1, Changeset, Version, ActorId, Timestamp
    from corrosion_tpu.types.change import ChunkedChanges

    async def main():
        origin = await launch_test_agent()
        # build a big version on the origin while it is alone
        stmts = [
            ["INSERT INTO tests (id, text) VALUES (?, ?)", [i, "y" * 600]]
            for i in range(60)
        ]
        origin.execute_transaction(stmts)
        changes = origin.storage.collect_changes((1, 1))
        last_seq = max(int(c.seq) for c in changes)
        chunks = list(ChunkedChanges(changes, 0, last_seq, max_buf_size=8192))
        assert len(chunks) >= 2, "need a multi-chunk version for this test"

        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            actor = ActorId(origin.actor_id)
            ts = Timestamp(int(origin.clock.new_timestamp()))
            # a gets even chunks, b gets odd chunks — nobody has all
            for i, (chunk, seqs) in enumerate(chunks):
                cs = Changeset.full(Version(1), chunk, seqs, last_seq, ts)
                cv = ChangeV1(actor_id=actor, changeset=cs)
                (a if i % 2 == 0 else b).handle_change(cv, ChangeSource.SYNC)
            assert 1 in a.bookie.for_actor(origin.actor_id).partials
            assert 1 in b.bookie.for_actor(origin.actor_id).partials
            await origin.stop()

            def both_complete():
                for x in (a, b):
                    bv = x.bookie.for_actor(origin.actor_id)
                    if not bv.contains_version(1) or 1 in bv.partials:
                        return False
                    n = x.storage.conn.execute(
                        "SELECT COUNT(*) FROM tests"
                    ).fetchone()[0]
                    if n != 60:
                        return False
                return True

            await wait_for(both_complete, timeout=20.0)
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_queries_endpoint_is_read_only(run):
    import json, urllib.request, urllib.error

    async def main():
        a = await launch_test_agent()
        try:
            url = f"http://{a.api_addr[0]}:{a.api_addr[1]}/v1/queries"
            req = urllib.request.Request(
                url, data=json.dumps("INSERT INTO tests (id) VALUES (99)").encode()
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 500
            assert "readonly" in exc.value.read().decode()
            # nothing was written, no version consumed
            assert a.storage.conn.execute(
                "SELECT COUNT(*) FROM tests"
            ).fetchone()[0] == 0
            assert a.storage.db_version() == 0
        finally:
            await a.stop()

    run(main())


def test_members_expose_connection_stats(run):
    """/v1/members carries per-peer transport stats once traffic has
    flowed (ConnectionStats parity, transport.rs:235-419)."""
    import json
    import urllib.request

    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'x')"]]
            )
            await wait_for(
                lambda: b.storage.read_query(
                    "SELECT count(*) FROM tests")[1] == [(1,)]
            )

            def peer_conn():
                url = f"http://{a.api_addr[0]}:{a.api_addr[1]}/v1/members"
                with urllib.request.urlopen(url, timeout=5) as resp:
                    data = json.load(resp)
                return [m.get("conn") for m in data["members"]]

            await wait_for(
                lambda: any(
                    c and c["connects"] >= 1 and c["bytes_sent"] > 0
                    and c["rtt_last_ms"] is not None
                    for c in peer_conn()
                ),
                timeout=10,
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_cluster_set_id_detaches_node(run):
    """``cluster set-id`` (corro-admin Cluster SetId): moving a node to
    another cluster id makes its gossip rejectable, so writes stop
    replicating to it, while same-id nodes still converge."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'pre')"]]
            )
            await wait_for(
                lambda: b.storage.read_query(
                    "SELECT count(*) FROM tests")[1] == [(1,)]
            )

            assert b.set_cluster_id(7) >= 1  # announced (and rejected)
            assert b.members.all() == []  # old-cluster members forgotten
            # a write on a no longer reaches b: cross-cluster uni
            # payloads are rejected at ingest
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (2, 'post')"]]
            )
            await asyncio.sleep(1.0)
            assert b.storage.read_query(
                "SELECT count(*) FROM tests")[1] == [(1,)]
            # membership detaches too: b's probes/refutations are now
            # dropped by a, so a's SWIM view of b decays to down
            await wait_for(lambda: not a.members.alive(), timeout=15)

            # out-of-range ids are refused before any state changes
            with pytest.raises(ValueError):
                b.set_cluster_id(1 << 16)
            assert b.config.cluster_id == 7
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_failed_changes_do_not_poison_the_batch(run):
    """A changeset mixing unapplyable changes (unknown table) with good
    ones applies the good rows, books the version so it is never
    re-fetched, and leaves the agent healthy (agent/tests.rs
    process_failed_changes)."""
    async def main():
        from corrosion_tpu.agent.pack import pack_values
        from corrosion_tpu.types import (
            ActorId, ChangeSource, ChangeV1, Changeset,
        )
        from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
        from corrosion_tpu.types.change import Change

        a = await launch_test_agent()
        b = await launch_test_agent(bootstrap=[addr_str(a)])
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            fake_site = bytes(range(16))

            def ch(i, table, pk_val, cid, val):
                return Change(
                    table=table, pk=pack_values([pk_val]), cid=cid, val=val,
                    col_version=1, db_version=CrsqlDbVersion(1),
                    seq=CrsqlSeq(i), site_id=fake_site, cl=1,
                )

            changes = [
                ch(0, "no_such_table", 1, "text", "bad"),
                ch(1, "tests", 77, "text", "good"),
                ch(2, "no_such_table", 2, "text", "bad2"),
            ]
            cv = ChangeV1(
                actor_id=ActorId(fake_site),
                changeset=Changeset.full(
                    Version(1), changes, (CrsqlSeq(0), CrsqlSeq(2)),
                    CrsqlSeq(2), a.clock.new_timestamp(),
                ),
            )
            a.enqueue_change(cv, ChangeSource.BROADCAST)
            await wait_for(
                lambda: a.storage.read_query(
                    "SELECT text FROM tests WHERE id=77")[1] == [("good",)]
            )
            # the version is booked applied: no lingering need/partial
            bv = a.bookie.for_actor(fake_site)
            assert bv.contains_version(1) and bv.partials == {}
            # the agent still takes local writes AND its broadcast path
            # is intact: a fresh write must reach the live peer
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (78, 'after')"]]
            )
            await wait_for(
                lambda: b.storage.read_query(
                    "SELECT text FROM tests WHERE id=78")[1] == [("after",)]
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_graceful_leave_marks_peer_down_immediately(run):
    """A clean shutdown announces departure (foca leave_cluster): the
    peer marks the leaver down at once instead of waiting out the
    probe -> suspect -> down cycle."""
    async def main():
        a = await launch_test_agent(suspect_timeout=30.0)
        b = await launch_test_agent(
            bootstrap=[addr_str(a)], suspect_timeout=30.0
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            b_actor = b.actor_id
            await b.stop()
            # far faster than the 30s suspicion path could possibly be
            await wait_for(
                lambda: (
                    (m := a.members.get(b_actor)) is not None
                    and m.state.value == "down"
                ),
                timeout=3.0,
            )
        finally:
            await a.stop()

    run(main())


def test_graceful_restart_rejoins_immediately(run, tmp_path):
    """A gracefully-stopped node that restarts from the same data dir
    comes back with a HIGHER incarnation, so its ALIVE announce
    overrides the DOWN record peers hold from the leave — rejoin is
    immediate, not at the mercy of piggyback self-refutation."""
    async def main():
        a = await launch_test_agent(suspect_timeout=30.0)
        d = str(tmp_path / "b")
        import os
        os.makedirs(d, exist_ok=True)
        b = await launch_test_agent(
            tmpdir=d, bootstrap=[addr_str(a)], suspect_timeout=30.0
        )
        b_actor = b.actor_id
        inc1 = b.incarnation
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            await b.stop()  # graceful: a marks b down instantly
            await wait_for(
                lambda: (m := a.members.get(b_actor)) and
                m.state.value == "down", timeout=3.0,
            )
            b = await launch_test_agent(
                tmpdir=d, bootstrap=[addr_str(a)], suspect_timeout=30.0
            )
            assert b.actor_id == b_actor  # same identity from the db
            assert b.incarnation > inc1  # renewed past the old life
            await wait_for(
                lambda: (m := a.members.get(b_actor)) and
                m.state.value == "alive", timeout=5.0,
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())
