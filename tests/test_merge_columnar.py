"""Columnar CRDT merge kernel (ops/merge.py, docs/crdts.md).

Pins the winner-selection core shared by the live batched apply and the
simulator's representation-independence check: encode semantics, the
merge rule on handcrafted streams, bit-equality of the NumPy twin and
the jit-compiled (shape-bucketed) JAX path, the hostile-field encode
fallback, and the sim-side ``ClusterObserver.kernel_state_check`` graft
with its seeded-corruption negative control.  Runs under
``JAX_PLATFORMS=cpu`` in tier-1 (the verify command's environment); the
JAX twin enables x64 explicitly since packed keys need int64 lanes.
"""

import random
from types import SimpleNamespace

import numpy as np
import pytest

from corrosion_tpu.ops import merge as mergeops


def _rand_batch(rng, n_pk=8, n_cid=4, n=None):
    n = n if n is not None else rng.randrange(1, 300)
    records = []
    for _ in range(n):
        pk = rng.randrange(n_pk)
        if rng.random() < 0.25:
            records.append((pk, None, rng.randrange(1, 6), 0, None))
        else:
            records.append((
                pk, f"c{rng.randrange(n_cid)}", rng.randrange(1, 6),
                rng.randrange(1, 5),
                rng.choice([None, 1, -4, 2.5, "x", b"\x01", "yy", ""]),
            ))
    seed_cls = {
        pk: rng.randrange(1, 5) for pk in range(n_pk)
        if rng.random() < 0.5
    }
    seed_cells = {}
    for pk in seed_cls:
        for c in range(n_cid):
            if rng.random() < 0.4:
                seed_cells[(pk, f"c{c}")] = (
                    rng.randrange(1, 5), rng.choice([None, 1, "z"]),
                )
    return records, seed_cls, seed_cells


def _decision_fields(dec):
    return {
        f: np.asarray(getattr(dec, f))
        for f in ("final_cl", "gen", "alive", "ensure", "sent_flag",
                  "clrow_idx", "winner_idx")
    }


def test_kernel_lww_and_generation_semantics():
    """Handcrafted stream against the merge rule (docs/crdts.md):
    higher cl wins the row (even = delete wipes cells), equal cl goes
    to col_version then the value order, and a later generation raise
    discards earlier in-batch winners."""
    records = [
        ("p1", "a", 1, 2, "v1"),     # accept (fresh cell)
        ("p1", "a", 1, 1, "stale"),  # lower col_version: reject
        ("p1", "a", 1, 2, "v2"),     # tie -> bigger value: accept
        ("p1", None, 2, 0, None),    # delete sentinel: wipes the cell
        ("p2", "a", 1, 1, "x"),      # accept
        ("p2", None, 3, 0, None),    # resurrect (odd): new generation
        ("p2", "a", 3, 1, "y"),      # accept in the new generation
        ("p3", "a", 1, 1, None),     # accept (NULL is a value)
        ("p3", "a", 1, 1, 5),        # tie -> INTEGER > NULL: accept
    ]
    plan = mergeops.encode_changes(records)
    dec = mergeops.select_winners(plan, backend="numpy")
    pk_ix = {pk: i for i, pk in enumerate(plan.pk_values)}
    cid_ix = {c: i for i, c in enumerate(plan.cid_values)}

    def winner(pk, cid):
        w = int(dec.winner_idx[pk_ix[pk] * plan.n_cid + cid_ix[cid]])
        return None if w < 0 else records[w][4]

    assert int(dec.final_cl[pk_ix["p1"]]) == 2
    assert not bool(dec.alive[pk_ix["p1"]])
    assert winner("p1", "a") is None  # wiped by the delete
    assert bool(dec.alive[pk_ix["p2"]])
    assert winner("p2", "a") == "y"
    assert winner("p3", "a") == 5
    # accept events: p1 a(x2), p1 delete, p2 a, p2 resurrect, p2 a,
    # p3 a(x2)
    assert dec.impacted == 8


def test_kernel_db_seed_participates_in_lww():
    """The prefetched DB view loses to a bigger in-batch write and
    beats a smaller one — and a fresh generation ignores it."""
    records = [
        ("p", "a", 1, 2, "small"),   # DB holds col_version 3: reject
        ("p", "b", 1, 3, "bigger"),  # beats the DB's col_version 2
        ("q", "a", 3, 1, "fresh"),   # generation above the DB's cl 1
    ]
    plan = mergeops.encode_changes(
        records,
        seed_cls={"p": 1, "q": 1},
        seed_cells={("p", "a"): (3, "db"), ("p", "b"): (2, "db"),
                    ("q", "a"): (9, "db")},
    )
    dec = mergeops.select_winners(plan, backend="numpy")
    pk_ix = {pk: i for i, pk in enumerate(plan.pk_values)}
    cid_ix = {c: i for i, c in enumerate(plan.cid_values)}
    assert int(
        dec.winner_idx[pk_ix["p"] * plan.n_cid + cid_ix["a"]]
    ) == -1
    assert int(
        dec.winner_idx[pk_ix["p"] * plan.n_cid + cid_ix["b"]]
    ) == 1
    # q's new generation wins despite the DB's huge col_version
    assert int(
        dec.winner_idx[pk_ix["q"] * plan.n_cid + cid_ix["a"]]
    ) == 2
    assert not bool(dec.ensure[pk_ix["q"]])
    assert bool(dec.gen[pk_ix["q"]])


def test_encode_fallback_on_hostile_fields():
    assert mergeops.encode_changes([]) is None
    # negative causal length cannot encode
    assert mergeops.encode_changes([("p", "a", -1, 1, "v")]) is None
    # a causal length beyond the 62-bit key budget cannot encode
    assert mergeops.encode_changes(
        [("p", "a", 1 << 63, 1, "v")]
    ) is None
    # an unsupported value type forces fallback when it is
    # tie-implicated (two candidates with the same (pk, cid, cl, ver)
    # would compare it); an untied value is never inspected — exactly
    # like the dict replay's lazily-called value_cmp
    assert mergeops.encode_changes(
        [("p", "a", 1, 1, object()), ("p", "a", 1, 1, object())]
    ) is None
    assert mergeops.encode_changes(
        [("p", "a", 1, 1, object())]
    ) is not None
    # NaN defeats the value total order: tie-implicated NaN falls back
    assert mergeops.encode_changes(
        [("p", "a", 1, 1, float("nan")), ("p", "a", 1, 1, 0.5)]
    ) is None
    # in-range batches do encode
    assert mergeops.encode_changes([("p", "a", 1, 1, "v")]) is not None


@pytest.mark.parametrize("trial", range(12))
def test_numpy_and_jax_twins_agree(trial):
    """The jitted, shape-bucketed JAX path returns bit-identical
    decisions to the NumPy twin on randomized batches."""
    from jax.experimental import enable_x64

    rng = random.Random(1000 + trial)
    records, seed_cls, seed_cells = _rand_batch(rng)
    plan = mergeops.encode_changes(records, seed_cls, seed_cells)
    assert plan is not None
    d_np = mergeops.select_winners(plan, backend="numpy")
    with enable_x64():
        d_jx = mergeops.select_winners(plan, backend="jax")
    f_np, f_jx = _decision_fields(d_np), _decision_fields(d_jx)
    for f in f_np:
        assert np.array_equal(f_np[f], f_jx[f]), f
    assert d_np.impacted == d_jx.impacted


def test_auto_backend_without_x64_uses_numpy():
    """backend="auto" must never require x64: big batches fall back to
    the NumPy twin when the jax path raises."""
    rng = random.Random(3)
    records, seed_cls, seed_cells = _rand_batch(rng, n=512)
    plan = mergeops.encode_changes(records, seed_cls, seed_cells)
    dec = mergeops.select_winners(plan, backend="auto")
    ref = mergeops.select_winners(plan, backend="numpy")
    assert dec.impacted == ref.impacted


# ---------------------------------------------------------------------------
# sim-side graft: ClusterObserver.kernel_state_check
# ---------------------------------------------------------------------------


def _mini_cluster(tmp_path):
    """Two converged CrConn 'nodes': node B applies node A's collected
    stream (writes, an overwrite, a delete, a resurrect)."""
    from corrosion_tpu.agent.storage import CrConn

    a = CrConn(str(tmp_path / "a.db"), site_id=b"\xaa" * 16)
    a.conn.executescript(
        "CREATE TABLE t (id INTEGER PRIMARY KEY NOT NULL, x, y);"
        "CREATE TABLE pko (k INTEGER PRIMARY KEY NOT NULL);"
    )
    a.as_crr("t")
    a.as_crr("pko")
    a.execute("INSERT INTO t (id, x, y) VALUES (1, 'one', 10)")
    a.execute("INSERT INTO t (id, x) VALUES (2, 'two')")
    a.execute("UPDATE t SET x = 'one-v2' WHERE id = 1")
    a.execute("DELETE FROM t WHERE id = 2")
    a.execute("INSERT INTO t (id, x) VALUES (2, 'reborn')")
    a.execute("INSERT INTO pko (k) VALUES (7)")
    b = CrConn(str(tmp_path / "b.db"), site_id=b"\xbb" * 16)
    b.conn.executescript(
        "CREATE TABLE t (id INTEGER PRIMARY KEY NOT NULL, x, y);"
        "CREATE TABLE pko (k INTEGER PRIMARY KEY NOT NULL);"
    )
    b.as_crr("t")
    b.as_crr("pko")
    b.apply_changes(a.collect_changes((1, a.db_version())))
    return a, b


def _observer(*conns):
    from corrosion_tpu.devcluster import ClusterObserver

    agents = {
        f"n{i}": SimpleNamespace(storage=c) for i, c in enumerate(conns)
    }
    return ClusterObserver(agents)


def test_kernel_state_check_passes_on_converged_cluster(tmp_path):
    a, b = _mini_cluster(tmp_path)
    try:
        res = _observer(a, b).kernel_state_check()
        assert res["ok"], res["violations"]
    finally:
        a.close()
        b.close()


def test_kernel_state_check_bites_on_corruption(tmp_path):
    """Negative control: a data row silently edited UNDER the clock
    representation (triggers suppressed) must trip the kernel check —
    bytewise node equality alone would never see it."""
    a, b = _mini_cluster(tmp_path)
    try:
        with a._lock:
            a.conn.execute("BEGIN IMMEDIATE")
            a._set_state("apply_mode", 1)  # suppress CRR triggers
            a.conn.execute(
                "UPDATE t SET x = 'tampered' WHERE id = 1"
            )
            a._set_state("apply_mode", 0)
            a.conn.execute("COMMIT")
        res = _observer(a, b).kernel_state_check()
        assert not res["ok"]
        assert any(
            v["kind"] == "kernel_cells" for v in res["violations"]
        )
        # a stray value in a column the kernel predicts NO winner for
        # is caught by the residual check — the "all nodes equally
        # wrong" direction bytewise equality and winner comparison
        # both miss.  A remote pk-only sentinel generation (bare
        # resurrect marker) creates a live row with every column at
        # its NULL default and no cell winners; then both nodes store
        # the same bogus value.
        from corrosion_tpu.agent.pack import pack_values
        from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
        from corrosion_tpu.types.change import Change, SENTINEL_CID

        with a._lock:  # undo the phase-1 tamper: clean slate
            a.conn.execute("BEGIN IMMEDIATE")
            a._set_state("apply_mode", 1)
            a.conn.execute("UPDATE t SET x = 'one-v2' WHERE id = 1")
            a._set_state("apply_mode", 0)
            a.conn.execute("COMMIT")
        bare = Change(
            table="t", pk=pack_values([5]), cid=SENTINEL_CID, val=None,
            col_version=1, db_version=CrsqlDbVersion(1),
            seq=CrsqlSeq(0), site_id=b"\xcc" * 16, cl=1,
        )
        for db in (a, b):
            db.apply_changes([bare])
        res = _observer(a, b).kernel_state_check()
        assert res["ok"], res["violations"]
        for db in (a, b):
            with db._lock:
                db.conn.execute("BEGIN IMMEDIATE")
                db._set_state("apply_mode", 1)
                db.conn.execute(
                    "UPDATE t SET y = 99 WHERE id = 5"
                )
                db._set_state("apply_mode", 0)
                db.conn.execute("COMMIT")
        res = _observer(a, b).kernel_state_check()
        assert any(
            v["kind"] == "kernel_residual" for v in res["violations"]
        )
        # a vanished row (liveness corruption) is also caught
        with a._lock:
            a.conn.execute("BEGIN IMMEDIATE")
            a._set_state("apply_mode", 1)
            a.conn.execute("DELETE FROM t WHERE id = 1")
            a._set_state("apply_mode", 0)
            a.conn.execute("COMMIT")
        res = _observer(a, b).kernel_state_check()
        assert any(
            v["kind"] == "kernel_liveness" for v in res["violations"]
        )
    finally:
        a.close()
        b.close()
