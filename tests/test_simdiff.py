"""Sim-vs-agent trace diff (small N; the recorded artifact runs at N=64)."""

import asyncio

from corrosion_tpu.sim.simdiff import agent_trace, diff_traces, sim_trace


def test_sim_trace_converges():
    t = sim_trace(64, seeds=4)
    assert t["converged_frac"] == 1.0
    assert t["msgs_per_node"] > 0
    assert t["ticks_to_converge_p50"] < 64


def test_agent_vs_sim_diff_small():
    """Boot a real 8-agent cluster and diff its convergence trace against
    the simulator under matched fanout/max_transmissions."""
    sim = sim_trace(8, fanout=3, max_transmissions=5, seeds=4)
    ag = asyncio.run(agent_trace(8, fanout=3, max_transmissions=5, timeout=30.0))
    d = diff_traces(sim, ag)
    assert d["diff"]["both_converged"]
    # same protocol, same parameters: message counts land in the same
    # regime (the sim models rounds, agents real time — allow slack)
    assert 0.1 < d["diff"]["msgs_per_node_ratio"] < 10.0
    assert d["agents"]["msgs_per_node"] > 0
