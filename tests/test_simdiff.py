"""Sim-vs-agent trace diff (small N; the recorded artifact runs at N=64)."""

import asyncio

from corrosion_tpu.sim.simdiff import agent_trace, diff_traces, sim_trace


def test_sim_trace_converges():
    t = sim_trace(64, seeds=4)
    assert t["converged_frac"] == 1.0
    assert t["msgs_per_node"] > 0
    assert t["ticks_to_converge_p50"] < 64


def test_agent_vs_sim_diff_small():
    """Boot a real 16-agent cluster and diff its convergence trace against
    the simulator under matched fanout/max_transmissions, comparing
    MEASURED hop depths (on-wire hop counter) and msgs/node."""
    sim = sim_trace(16, fanout=3, max_transmissions=5, seeds=4)
    ag = asyncio.run(
        agent_trace(16, fanout=3, max_transmissions=5, writes=3, timeout=30.0)
    )
    d = diff_traces(sim, ag)
    assert d["diff"]["both_converged"]
    # every node must have a measured hop depth (origin = synthetic 0)
    assert ag["hops_measured"] == 3 * 16
    assert ag["hops_p50"] >= 1
    # same protocol, same parameters: measured quantities land in the
    # same regime (sent_to residual allows slack at small N)
    assert 0.3 < d["diff"]["msgs_per_node_ratio"] < 3.5
    assert 0.3 < d["diff"]["hops_p50_ratio"] < 3.5
    assert d["agents"]["msgs_per_node"] > 0
