"""Golden tests for the sync needs algebra.

The scenario sequence mirrors the reference's
``crates/corro-types/src/sync.rs`` unit test for ``compute_available_needs``
so our host-side algebra is behaviorally identical.
"""

from corrosion_tpu.types import ActorId, SyncStateV1, SyncNeedV1, Version


def test_compute_available_needs_reference_scenarios():
    actor1 = ActorId.generate()

    ours = SyncStateV1(actor_id=ActorId.generate())
    ours.heads[actor1] = Version(10)

    theirs = SyncStateV1(actor_id=ActorId.generate())
    theirs.heads[actor1] = Version(13)

    # 1) head catch-up only
    assert ours.compute_available_needs(theirs) == {
        actor1: [SyncNeedV1.full(11, 13)]
    }

    # 2) plus our own gap ranges
    ours.need.setdefault(actor1, []).append((2, 5))
    ours.need.setdefault(actor1, []).append((7, 7))
    assert ours.compute_available_needs(theirs) == {
        actor1: [
            SyncNeedV1.full(2, 5),
            SyncNeedV1.full(7, 7),
            SyncNeedV1.full(11, 13),
        ]
    }

    # 3) plus a partial version they fully have
    ours.partial_need[actor1] = {Version(9): [(100, 120), (130, 132)]}
    assert ours.compute_available_needs(theirs) == {
        actor1: [
            SyncNeedV1.full(2, 5),
            SyncNeedV1.full(7, 7),
            SyncNeedV1.partial(9, [(100, 120), (130, 132)]),
            SyncNeedV1.full(11, 13),
        ]
    }

    # 4) they are partial too: only complementary seqs are available
    theirs.partial_need[actor1] = {Version(9): [(100, 110), (130, 130)]}
    assert ours.compute_available_needs(theirs) == {
        actor1: [
            SyncNeedV1.full(2, 5),
            SyncNeedV1.full(7, 7),
            SyncNeedV1.partial(9, [(111, 120), (131, 132)]),
            SyncNeedV1.full(11, 13),
        ]
    }


def test_zero_head_and_self_ignored():
    me = ActorId.generate()
    other_actor = ActorId.generate()
    ours = SyncStateV1(actor_id=me)
    theirs = SyncStateV1(actor_id=ActorId.generate())
    theirs.heads[me] = Version(5)  # our own actor: ignored
    theirs.heads[other_actor] = Version(0)  # zero head: ignored
    assert ours.compute_available_needs(theirs) == {}


def test_need_len():
    a = ActorId.generate()
    st = SyncStateV1(actor_id=ActorId.generate())
    st.need[a] = [(1, 10), (20, 20)]
    st.partial_need[a] = {Version(30): [(0, 99)]}
    # 11 full + 100 seqs // 50 = 2 chunks
    assert st.need_len() == 13
    assert st.need_len_for_actor(a) == 12
