"""Batched change-application pipeline tests.

The contract under test: ``CrConn.apply_changes_batched`` must leave
the database in EXACTLY the state the per-change reference path
(``_apply_one`` via ``apply_changes_sequential_in_tx``) leaves it in —
data tables, clock tables, causal-length tables, compaction impact
records, site interning order, ``collect_changes`` output and the
rows-impacted count — across shuffled, duplicated and superseded
change streams.  Plus the runtime half of the pipeline: merged apply
transactions, off-loop uni decode, the JSON→speedy partial-buffer
migration, and shutdown-cancellation accounting.
"""

import asyncio
import random

import pytest

from corrosion_tpu.agent import wire
from corrosion_tpu.agent.pack import pack_values
from corrosion_tpu.agent.storage import CrConn
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import ActorId, Changeset, ChangeSource, ChangeV1
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
from corrosion_tpu.types.change import Change, SENTINEL_CID
from corrosion_tpu.types.hlc import Timestamp

# `items` columns are UNTYPED (BLOB affinity): stored values roundtrip
# verbatim, so the randomized generator may throw any value type at
# them.  `typed` exercises declared affinities with affinity-stable
# values (the shape real change streams have: an origin collects values
# it already stored).  `pkonly` exercises the sentinel-only shape.
SCHEMA = """
CREATE TABLE IF NOT EXISTS items (
  id INTEGER PRIMARY KEY NOT NULL, a, b, c);
CREATE TABLE IF NOT EXISTS typed (
  id INTEGER PRIMARY KEY NOT NULL,
  name TEXT NOT NULL DEFAULT '',
  n INTEGER);
CREATE TABLE IF NOT EXISTS pkonly (k INTEGER PRIMARY KEY NOT NULL);
"""

SITES = [bytes([i]) * 16 for i in range(1, 4)]


def _mk(tmp_path, name, columnar=None):
    """A CRR database; ``columnar`` pins the batched merge backend:
    True forces the columnar kernel for EVERY batch size, False forces
    the dict-replay oracle, None keeps the production dispatch."""
    conn = CrConn(str(tmp_path / f"{name}.db"), site_id=b"\x77" * 16)
    if columnar is True:
        conn.columnar_merge_min = 0
    elif columnar is False:
        conn.columnar_merge = False
    conn.conn.executescript(SCHEMA)
    for t in ("items", "typed", "pkonly"):
        conn.as_crr(t)
    return conn


def _rand_val(rng, table, cid=None):
    # Values are AFFINITY-STABLE for their columns, the invariant every
    # collect_changes-produced stream holds (an origin ships the value
    # it already stored, post-affinity): strings into TEXT, ints into
    # INTEGER, anything into the untyped (BLOB-affinity) columns.  A
    # stream violating this can diverge from the per-change path only
    # in redundant-rewrite accounting on exact value ties — see the
    # batched-apply contract note in agent/storage.py.
    if table == "typed":
        if cid == "name":
            return rng.choice(["alpha", "beta", "", "zzz", "-3"])
        return rng.choice([1, 7, -3, 0, None, 123456])
    return rng.choice([
        None, 0, 1, -5, 2.5, -0.25, "x", "yy", "", b"", b"\x00\x01",
        b"\xff", 123456789, "unicode-é",
    ])


def _rand_change(rng):
    table = rng.choice(["items", "items", "typed", "pkonly"])
    pk = pack_values([rng.randrange(6)])
    site = rng.choice(SITES)
    dbv = rng.randrange(1, 50)
    seq = rng.randrange(0, 200)
    cl = rng.randrange(1, 5)
    if table == "pkonly" or rng.random() < 0.2:
        return Change(
            table=table, pk=pk, cid=SENTINEL_CID, val=None,
            col_version=cl, db_version=CrsqlDbVersion(dbv),
            seq=CrsqlSeq(seq), site_id=site, cl=cl,
        )
    cid = rng.choice(["a", "b", "c"] if table == "items" else ["name", "n"])
    return Change(
        table=table, pk=pk, cid=cid, val=_rand_val(rng, table, cid),
        col_version=rng.randrange(1, 4), db_version=CrsqlDbVersion(dbv),
        seq=CrsqlSeq(seq), site_id=site, cl=cl,
    )


def _stream(rng, n):
    """A hostile stream: random changes, duplicated entries, superseded
    same-cell writes, then shuffled."""
    out = [_rand_change(rng) for _ in range(n)]
    # duplicates (re-delivery) and superseded rewrites of earlier cells
    for _ in range(n // 4):
        out.append(rng.choice(out))
    for _ in range(n // 4):
        base = rng.choice(out)
        if base.cid != SENTINEL_CID:
            out.append(Change(
                table=base.table, pk=base.pk, cid=base.cid,
                val=_rand_val(rng, base.table, base.cid),
                col_version=rng.randrange(1, 5),
                db_version=base.db_version, seq=base.seq,
                site_id=base.site_id, cl=base.cl,
            ))
    rng.shuffle(out)
    return out


def _dump(c):
    """Every piece of observable CRDT state, order-normalized."""
    out = {}
    for t in ("items", "typed", "pkonly"):
        out[f"{t}.data"] = sorted(
            c.conn.execute(f'SELECT * FROM "{t}"').fetchall(),
            key=repr,
        )
        out[f"{t}.clock"] = sorted(c.conn.execute(
            f'SELECT pk, cid, col_version, db_version, seq, site_ordinal '
            f'FROM "{t}__corro_clock"').fetchall())
        out[f"{t}.cl"] = sorted(c.conn.execute(
            f'SELECT pk, cl, db_version, seq, site_ordinal, sentinel '
            f'FROM "{t}__corro_cl"').fetchall())
    out["sites"] = c.conn.execute(
        "SELECT ordinal, site_id FROM __corro_sites ORDER BY ordinal"
    ).fetchall()
    out["impacted"] = sorted(c.conn.execute(
        "SELECT site_ordinal, db_version FROM __corro_versions_impacted"
    ).fetchall())
    return out


def _assert_state_equal(seq_db, bat_db):
    ds, db_ = _dump(seq_db), _dump(bat_db)
    for key in ds:
        assert ds[key] == db_[key], f"divergence in {key}"
    # collect_changes must agree for every interned origin site
    for site in SITES + [seq_db.site_id]:
        s = seq_db.collect_changes((1, 64), None if site == seq_db.site_id else site)
        b = bat_db.collect_changes((1, 64), None if site == bat_db.site_id else site)
        assert s == b, f"collect_changes diverged for site {site[:1].hex()}"


def _three_way_round(rng, dbs, n=40):
    """One hostile stream through all arms: the `_apply_one` sequential
    oracle, the dict-replay batched path, and the columnar kernel.
    Asserts rows-impacted and full observable state agree."""
    a, dict_db, col_db = dbs
    batch = _stream(rng, n)
    with a.apply_tx():
        n_seq = a.apply_changes_sequential_in_tx(list(batch))
    n_dict = dict_db.apply_changes_batched(list(batch))
    n_col = col_db.apply_changes_batched(list(batch))
    assert n_seq == n_dict == n_col, "rows-impacted diverged"
    _assert_state_equal(a, dict_db)
    _assert_state_equal(a, col_db)


def _mk_three(tmp_path, tag):
    """The three arms with identical local writes first, so remote
    applies can overwrite local change rows and exercise the compaction
    impact triggers."""
    dbs = (
        _mk(tmp_path, f"seq{tag}"),
        _mk(tmp_path, f"dict{tag}", columnar=False),
        _mk(tmp_path, f"col{tag}", columnar=True),
    )
    for c in dbs:
        c.execute(
            "INSERT INTO items (id, a, b) VALUES (1, 'local', 0)")
        c.execute("INSERT INTO typed (id, name, n) VALUES (2, 'loc', 7)")
        c.execute("INSERT INTO pkonly (k) VALUES (3)")
    return dbs


@pytest.mark.parametrize("seed", range(8))
def test_batched_apply_parity_randomized(tmp_path, seed):
    """Three-way equivalence — columnar kernel vs dict replay vs the
    `_apply_one` sequential oracle — over shuffled, duplicated and
    superseded streams with sentinel/delete generations."""
    rng = random.Random(seed)
    dbs = _mk_three(tmp_path, seed)
    for _round in range(3):
        _three_way_round(rng, dbs)
    for c in dbs:
        c.close()


@pytest.mark.slow
@pytest.mark.parametrize("block", range(10))
def test_batched_apply_parity_fuzz_200(tmp_path, block):
    """The offline fuzz tier: 200 seeds (20 per block) of the same
    three-way equivalence, disjoint from the tier-1 seed range."""
    for seed in range(100 + block * 20, 120 + block * 20):
        rng = random.Random(seed)
        dbs = _mk_three(tmp_path, seed)
        for _round in range(2):
            _three_way_round(rng, dbs)
        for c in dbs:
            c.close()


def test_columnar_corruption_is_caught(tmp_path, monkeypatch):
    """Seeded-corruption negative control: a columnar decision with one
    winner dropped MUST trip the parity checker — proving the
    three-way suite actually bites on kernel divergence."""
    import dataclasses

    import numpy as np

    from corrosion_tpu.ops import merge as mergeops

    real = mergeops.select_winners

    def corrupt(plan, backend="auto"):
        dec = real(plan, backend=backend)
        w = dec.winner_idx.copy()
        nz = np.flatnonzero(w >= 0)
        assert len(nz), "corruption control needs at least one winner"
        w[nz[0]] = -1
        return dataclasses.replace(dec, winner_idx=w)

    monkeypatch.setattr(mergeops, "select_winners", corrupt)
    rng = random.Random(5)
    dbs = _mk_three(tmp_path, "corrupt")
    with pytest.raises(AssertionError):
        _three_way_round(rng, dbs)
    for c in dbs:
        c.close()


def test_batched_apply_parity_interleaves_with_local_writes(tmp_path):
    """Remote batches between local writes: version counters, triggers
    and backfill bookkeeping stay identical."""
    rng = random.Random(99)
    a = _mk(tmp_path, "seq-mix")
    b = _mk(tmp_path, "bat-mix")
    for i in range(3):
        for c in (a, b):
            c.execute(
                "INSERT OR REPLACE INTO items (id, a) VALUES (?, ?)",
                (i, f"w{i}"),
            )
        batch = _stream(rng, 25)
        with a.apply_tx():
            a.apply_changes_sequential_in_tx(list(batch))
        b.apply_changes_batched(list(batch))
        assert a.db_version() == b.db_version()
        _assert_state_equal(a, b)
    a.close()
    b.close()


def test_batched_apply_empty_and_tiny(tmp_path):
    a = _mk(tmp_path, "tiny")
    assert a.apply_changes_batched([]) == 0
    ch = Change(
        table="items", pk=pack_values([9]), cid="a", val="v",
        col_version=1, db_version=CrsqlDbVersion(1), seq=CrsqlSeq(0),
        site_id=SITES[0], cl=1,
    )
    assert a.apply_changes_batched([ch]) == 1
    # idempotent re-apply through the dispatching entry point
    assert a.apply_changes([ch, ch, ch]) == 0
    assert a.conn.execute(
        "SELECT a FROM items WHERE id=9").fetchone() == ("v",)
    a.close()


def test_batched_apply_unknown_table_is_skipped(tmp_path):
    a = _mk(tmp_path, "unk")
    ch = Change(
        table="nope", pk=pack_values([1]), cid="x", val=1,
        col_version=1, db_version=CrsqlDbVersion(1), seq=CrsqlSeq(0),
        site_id=SITES[0], cl=1,
    )
    assert a.apply_changes_batched([ch] * 5) == 0
    a.close()


# ---------------------------------------------------------------------------
# partial-buffer blob format: speedy with versioned prefix, JSON legacy
# ---------------------------------------------------------------------------


def _sample_change(val="hello"):
    return Change(
        table="items", pk=pack_values([4]), cid="a", val=val,
        col_version=3, db_version=CrsqlDbVersion(9), seq=CrsqlSeq(2),
        site_id=SITES[1], cl=1,
    )


@pytest.mark.parametrize(
    "val", [None, 5, -7, 2.25, "txt", b"\x00\xfe", ""]
)
def test_buffered_blob_roundtrip_speedy(val):
    ch = _sample_change(val)
    blob = wire.encode_buffered_change(ch)
    assert blob[0] == wire.BUFFERED_BLOB_SPEEDY
    assert blob[1:] == speedy.encode_change(ch)
    assert wire.decode_buffered_change(blob) == ch


def test_buffered_blob_decodes_legacy_json():
    ch = _sample_change()
    legacy = wire.encode_datagram(wire.change_to_dict(ch))
    assert legacy[:1] == b"{"
    assert wire.decode_buffered_change(legacy) == ch


def test_buffered_blob_unknown_prefix_raises():
    with pytest.raises(ValueError):
        wire.decode_buffered_change(b"\x7fjunk")


def test_partial_promotion_reads_mixed_blob_formats(tmp_path):
    """A database carrying legacy JSON buffered rows (written before the
    binary format) promotes a completed version correctly when the
    missing chunk arrives through the new pipeline."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        cfg = AgentConfig(
            db_path=str(tmp_path / "agent.db"),
            schema_sql=SCHEMA,
            api_port=None,
        )
        agent = Agent(cfg)
        try:
            actor = SITES[2]
            ts = Timestamp(1)
            ch0 = Change(
                table="items", pk=pack_values([11]), cid="a", val="old",
                col_version=1, db_version=CrsqlDbVersion(1),
                seq=CrsqlSeq(0), site_id=actor, cl=1,
            )
            ch1 = Change(
                table="items", pk=pack_values([11]), cid="b", val="new",
                col_version=1, db_version=CrsqlDbVersion(1),
                seq=CrsqlSeq(1), site_id=actor, cl=1,
            )
            # chunk 1 (seq 0) buffered through the live path...
            cv0 = ChangeV1(
                actor_id=ActorId(actor),
                changeset=Changeset.full(
                    Version(1), [ch0], (CrsqlSeq(0), CrsqlSeq(0)),
                    last_seq=CrsqlSeq(1), ts=ts,
                ),
            )
            assert agent.handle_change(cv0, ChangeSource.SYNC)
            # ...then rewritten in place as a LEGACY JSON blob, as an
            # old database would hold it
            legacy = wire.encode_datagram(wire.change_to_dict(ch0))
            with agent.storage._lock:
                agent.storage.conn.execute(
                    "UPDATE __corro_buffered_changes SET change=? "
                    "WHERE actor_id=? AND version=1 AND seq=0",
                    (legacy, actor),
                )
            cv1 = ChangeV1(
                actor_id=ActorId(actor),
                changeset=Changeset.full(
                    Version(1), [ch1], (CrsqlSeq(1), CrsqlSeq(1)),
                    last_seq=CrsqlSeq(1), ts=ts,
                ),
            )
            assert agent.handle_change(cv1, ChangeSource.SYNC)
            row = agent.storage.conn.execute(
                "SELECT a, b FROM items WHERE id=11").fetchone()
            assert row == ("old", "new")
            booked = agent.bookie.for_actor(actor)
            assert booked.contains_version(1)
            assert 1 not in booked.partials
        finally:
            agent.storage.close()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# runtime: merged apply transactions + off-loop decode + cancellation
# ---------------------------------------------------------------------------


def _complete_cv(actor, version, pk, val, ts=7):
    ch = Change(
        table="items", pk=pack_values([pk]), cid="a", val=val,
        col_version=1, db_version=CrsqlDbVersion(version),
        seq=CrsqlSeq(0), site_id=actor, cl=1,
    )
    return ChangeV1(
        actor_id=ActorId(actor),
        changeset=Changeset.full(
            Version(version), [ch], (CrsqlSeq(0), CrsqlSeq(0)),
            last_seq=CrsqlSeq(0), ts=Timestamp(ts),
        ),
    )


def test_apply_batch_merges_consecutive_changesets(tmp_path):
    """Consecutive complete changesets from one actor apply in ONE
    merged transaction with correct per-changeset news flags, and the
    bookkeeping matches the per-changeset path."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        agent = Agent(AgentConfig(
            db_path=str(tmp_path / "merge.db"), schema_sql=SCHEMA,
            api_port=None,
        ))
        try:
            actor = SITES[0]
            cvs = [_complete_cv(actor, v, pk=v, val=f"v{v}")
                   for v in (1, 2, 3)]
            dup = cvs[1]
            batch = [(cv, ChangeSource.SYNC) for cv in cvs]
            batch.append((dup, ChangeSource.SYNC))
            commits_before = agent.storage.conn.execute(
                "PRAGMA data_version").fetchone()[0]
            out = agent._apply_batch(batch)
            assert [news for _cv, _s, news, _meta in out] == [
                True, True, True, False,
            ]
            booked = agent.bookie.for_actor(actor)
            assert booked.last() == 3
            for v in (1, 2, 3):
                assert booked.contains_version(v)
            rows = agent.storage.conn.execute(
                "SELECT id, a FROM items ORDER BY id").fetchall()
            assert rows == [(1, "v1"), (2, "v2"), (3, "v3")]
            # bookkeeping rows persisted (restart = resume)
            persisted = agent.storage.conn.execute(
                "SELECT start_version, db_version, last_seq FROM "
                "__corro_bookkeeping WHERE actor_id=? "
                "ORDER BY start_version", (actor,),
            ).fetchall()
            assert persisted == [(1, 1, 0), (2, 2, 0), (3, 3, 0)]
            del commits_before
        finally:
            agent.storage.close()

    asyncio.run(main())


def test_apply_batch_decodes_raw_uni_payloads_off_loop(tmp_path):
    """Raw (undecoded) uni payloads enqueued by the stream server are
    decoded inside the apply worker, deduped and applied."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        agent = Agent(AgentConfig(
            db_path=str(tmp_path / "raw.db"), schema_sql=SCHEMA,
            api_port=None,
        ))
        try:
            actor = SITES[1]
            cv = _complete_cv(actor, 1, pk=21, val="raw")
            frame = agent.encode_broadcast_frame(cv)
            payloads = speedy.FrameReader().feed(frame)
            assert len(payloads) == 1
            agent._ingest_uni_payloads(payloads)
            assert len(agent._ingest) == 1
            item, source = agent._ingest[0]
            # raw items carry (payload, delivering_peer) so a failed
            # signature can blame the transport (signed attribution)
            assert source is None
            payload, peer = item
            assert isinstance(payload, (bytes, bytearray)) and peer is None
            batch = list(agent._ingest)
            agent._ingest.clear()
            out = agent._apply_batch(batch)
            assert len(out) == 1 and out[0][2] is True
            assert agent.storage.conn.execute(
                "SELECT a FROM items WHERE id=21").fetchone() == ("raw",)
            # garbage payloads are dropped without poisoning the batch
            out = agent._apply_batch([((b"\xde\xad\xbe\xef", None), None)])
            assert out == []
            # and rejected at ENQUEUE by the prelude check, so a junk
            # burst cannot evict real changesets from the bounded queue
            agent._ingest_uni_payloads([b"\xde\xad\xbe\xef" * 8])
            assert len(agent._ingest) == 0
            assert agent.metrics.get_counter(
                "corro_wire_decode_errors_total") >= 1
            # a payload passing the prelude check but raising a
            # NON-SpeedyError deep in decode (invalid UTF-8 in a string
            # field) is skipped without aborting the batch's valid work
            w = speedy.Writer()
            w.tag(0).tag(0).tag(0)          # UniPayload/Broadcast/Change
            w.raw(SITES[1])                 # actor
            w.tag(1)                        # Changeset::Full
            w.u64(5)                        # version
            w.u32(1)                        # one change
            w.lp_bytes(b"\xff\xfe")         # table name: invalid UTF-8
            hostile = w.getvalue()
            good = _complete_cv(SITES[2], 1, pk=22, val="ok")
            out = agent._apply_batch([
                ((hostile, None), None), (good, ChangeSource.SYNC),
            ])
            assert len(out) == 1 and out[0][2] is True
            assert agent.storage.conn.execute(
                "SELECT a FROM items WHERE id=22").fetchone() == ("ok",)
        finally:
            agent.storage.close()

    asyncio.run(main())


def test_merged_group_failure_falls_back_per_changeset(tmp_path):
    """If the merged transaction fails AFTER the in-memory bookkeeping
    moved (e.g. the bookkeeping flush), memory is restored from the
    snapshot so the per-changeset fallback re-applies every changeset
    instead of skipping them as already-contained."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        agent = Agent(AgentConfig(
            db_path=str(tmp_path / "fallback.db"), schema_sql=SCHEMA,
            api_port=None,
        ))
        try:
            actor = SITES[0]
            cvs = [_complete_cv(actor, v, pk=40 + v, val=f"f{v}")
                   for v in (1, 2)]
            orig = agent.bookie.persist_versions
            calls = {"n": 0}

            def boom(*a, **kw):
                calls["n"] += 1
                raise RuntimeError("flush failed")

            agent.bookie.persist_versions = boom
            try:
                out = agent._apply_batch(
                    [(cv, ChangeSource.SYNC) for cv in cvs]
                )
            finally:
                agent.bookie.persist_versions = orig
            assert calls["n"] == 1
            # the merge abort has its own series; the recovered retry
            # must NOT read as an apply error
            assert agent.metrics.get_counter(
                "corro_apply_group_fallbacks_total") == 1
            assert agent.metrics.get_counter(
                "corro_changes_apply_errors_total") == 0
            # fallback re-applied both in their own transactions
            assert [news for _cv, _s, news, _meta in out] == [True, True]
            rows = agent.storage.conn.execute(
                "SELECT id, a FROM items WHERE id >= 41 ORDER BY id"
            ).fetchall()
            assert rows == [(41, "f1"), (42, "f2")]
            booked = agent.bookie.for_actor(actor)
            assert booked.contains_version(1)
            assert booked.contains_version(2)
            # and the bookkeeping rows exist (written by the fallback)
            persisted = agent.storage.conn.execute(
                "SELECT start_version FROM __corro_bookkeeping "
                "WHERE actor_id=? ORDER BY start_version", (actor,),
            ).fetchall()
            assert persisted == [(1,), (2,)]
        finally:
            agent.storage.close()

    asyncio.run(main())


def test_finish_apply_reraises_cancellation(tmp_path):
    """A shutdown-time CancelledError must propagate, not count into
    corro_changes_apply_errors_total."""
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        agent = Agent(AgentConfig(
            db_path=str(tmp_path / "cancel.db"), schema_sql=SCHEMA,
            api_port=None,
        ))
        try:
            fut = asyncio.get_running_loop().create_future()
            fut.cancel()
            await asyncio.sleep(0)
            before = agent.metrics.get_counter(
                "corro_changes_apply_errors_total")
            with pytest.raises(asyncio.CancelledError):
                agent._finish_apply(fut)
            assert agent.metrics.get_counter(
                "corro_changes_apply_errors_total") == before
            # a real failure still counts
            bad = asyncio.get_running_loop().create_future()
            bad.set_exception(RuntimeError("boom"))
            agent._finish_apply(bad)
            assert agent.metrics.get_counter(
                "corro_changes_apply_errors_total") == before + 1
        finally:
            agent.storage.close()

    asyncio.run(main())


def test_apply_batch_records_apply_seconds(tmp_path):
    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    async def main():
        agent = Agent(AgentConfig(
            db_path=str(tmp_path / "hist.db"), schema_sql=SCHEMA,
            api_port=None,
        ))
        try:
            cv = _complete_cv(SITES[0], 1, pk=31, val="t")
            agent._apply_batch([(cv, ChangeSource.SYNC)])
            rendered = agent.metrics.render()
            assert "corro_apply_seconds" in rendered
        finally:
            agent.storage.close()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# bench smoke: the batched path is exercised (not timed) in tier-1;
# the timed 10k acceptance run is marked slow
# ---------------------------------------------------------------------------


def test_apply_bench_smoke_500():
    from bench import run_apply_bench

    out = run_apply_bench(sizes=(500,), out_path=None)
    assert out["points"], "no benchmark points produced"
    for p in out["points"]:
        assert "error" not in p, p
        assert p["per_change"]["rows_impacted"] == \
            p["batched"]["rows_impacted"]
        # in-bench parity: byte-identical CRDT state per point, with
        # the columnar kernel on the batched arm (500 >= threshold)
        assert p["parity"] is True
        assert p["kernel"] == "columnar"


@pytest.mark.slow
def test_apply_bench_10k_speedup():
    from bench import run_apply_bench

    out = run_apply_bench(sizes=(1000, 10000), out_path=None)
    for p in out["points"]:
        assert "error" not in p, p
        assert p["parity"] is True, p
    headline = next(
        p for p in out["points"]
        if p["n_changes"] == 10000 and p["mode"] == "cold"
    )
    assert headline["speedup"] >= 4.0, headline
    assert headline["kernel"] == "columnar"
    assert out["kernel_ab"]["pass"] is True
    assert out["stall_gate"]["pass"] is True
