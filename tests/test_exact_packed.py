"""Bitpacked exact-sampler kernel (sim/calibrate.py, headline scale).

The rejection sampler must agree with the scores-based exact kernel
under matched conditions (same protocol, different algorithm, same
distribution), and its bitpacked ``sent_to`` bookkeeping must be
self-consistent (msgs == popcount of marked bits when no sync traffic
is charged).
"""

import numpy as np
import pytest

from corrosion_tpu.sim.calibrate import (
    ExactConfig,
    HeadlineExactConfig,
    packed_exact_init,
    packed_exact_tick,
    run_exact,
    run_exact_headline,
)


def test_matches_scores_kernel_distribution():
    """Same protocol, two independent exact samplers: msgs/node means
    must agree within a few percent at N=1000 (both uniform
    without-replacement with sent_to exclusion, no loss/sync/ring0)."""
    cfg = HeadlineExactConfig(
        n_nodes=1000, fanout=4, ring0_size=0, max_transmissions=8,
        max_ticks=64, chunk_ticks=8,
    )
    packed = run_exact_headline(cfg, n_seeds=3, seed=0)
    scores = [
        run_exact(
            ExactConfig(n_nodes=1000, fanout=4, max_transmissions=8,
                        max_ticks=64),
            seed=s,
        )["msgs_per_node_mean"]
        for s in range(3)
    ]
    assert packed["converged_frac"] == 1.0
    assert packed["msgs_per_node_mean"] == pytest.approx(
        float(np.mean(scores)), rel=0.06
    )


def test_msgs_equals_popcount_of_sent_bits():
    """Every charged broadcast message marks exactly one sent_to bit
    (and vice versa): per-node msgs == popcount of the node's packed
    row.  Ring0 seeding included (the origin's tier is marked+charged
    at init); sync off so no session messages pollute the invariant."""
    import jax

    cfg = HeadlineExactConfig(
        n_nodes=1200, fanout=4, ring0_size=64, max_transmissions=4,
        loss=0.1, max_ticks=32, chunk_ticks=8,
    )
    key = jax.random.PRNGKey(7)
    state = packed_exact_init(cfg, jax.random.fold_in(key, 99))
    for t in range(10):
        state = packed_exact_tick(state, jax.random.fold_in(key, t), cfg)
    msgs = np.asarray(state.msgs)
    pop = np.unpackbits(
        np.asarray(state.sent), axis=1, bitorder="little"
    ).sum(axis=1)
    assert (msgs == pop).all()
    assert msgs[0] >= 63  # origin charged its ring0 tier


def test_partition_isolates_without_sync():
    """While the partition is active and sync is off, no cross-block
    infection can occur — pins the partition mask."""
    import jax

    cfg = HeadlineExactConfig(
        n_nodes=512, fanout=4, ring0_size=0, max_transmissions=8,
        partition_blocks=2, heal_tick=1000, sync_interval=0,
        max_ticks=32, chunk_ticks=8,
    )
    key = jax.random.PRNGKey(0)
    state = packed_exact_init(cfg, jax.random.fold_in(key, 99))
    for t in range(12):
        state = packed_exact_tick(state, jax.random.fold_in(key, t), cfg)
    infected = np.asarray(state.infected)
    assert infected[: 256].any()
    assert not infected[256:].any()


def test_sync_heals_partition_after_heal_tick():
    """The full headline shape (loss + partition + heal + sync)
    converges; convergence cannot precede the heal tick."""
    cfg = HeadlineExactConfig(
        n_nodes=2000, fanout=4, ring0_size=256, max_transmissions=8,
        loss=0.05, partition_blocks=2, heal_tick=12,
        sync_interval=8, sync_peers=1, max_ticks=96, chunk_ticks=8,
    )
    r = run_exact_headline(cfg, n_seeds=2, seed=0)
    assert r["converged_frac"] == 1.0
    assert r["ticks_p50"] > 12


def test_seed_batched_runner_matches_sequential():
    """Seed-parallel batches (vmapped tick; the rejection while_loop
    batches to loop-while-any with frozen finished seeds) must produce
    the SAME per-seed rank statistics as one-seed-at-a-time runs —
    lifting the seed cap cannot move the published numbers."""
    cfg = HeadlineExactConfig(
        n_nodes=1000, fanout=4, ring0_size=64, max_transmissions=8,
        loss=0.05, sync_interval=4, max_ticks=64, chunk_ticks=8,
    )
    seq = run_exact_headline(cfg, n_seeds=5, seed=0, seed_batch=1)
    bat = run_exact_headline(cfg, n_seeds=5, seed=0, seed_batch=5)
    # 5 seeds in batches of 2+2+1: the pipelined-batches path
    mix = run_exact_headline(cfg, n_seeds=5, seed=0, seed_batch=2)
    for k in ("converged_frac", "ticks_p50", "ticks_p99",
              "msgs_per_node_mean", "msgs_per_node_p99"):
        assert seq[k] == bat[k] == mix[k], k
    assert bat["seed_batch"] == 5 and mix["seed_batch"] == 2


def test_seed_batch_policy_tracks_bitmap_budget():
    """The HBM policy: batch size shrinks with the per-shard bitmap
    and grows with shard count, clamped to [1, n_seeds, 32]."""
    from corrosion_tpu.sim.calibrate import exact_seed_batch

    small = HeadlineExactConfig(n_nodes=1000)
    big = HeadlineExactConfig(n_nodes=256_000)
    assert exact_seed_batch(small, 32) == 32
    # 256k single-chip: 8.2 GB bitmap -> one seed at a time
    assert exact_seed_batch(big, 16, n_shards=1) == 1
    # sharded 8-ways the same budget fits several seeds
    assert exact_seed_batch(big, 16, n_shards=8) > \
        exact_seed_batch(big, 16, n_shards=1)
    # explicit budget override is respected
    assert exact_seed_batch(small, 32, hbm_budget_bytes=1) == 1
    assert exact_seed_batch(small, 4) == 4


def test_rejection_guard_rejects_tiny_n():
    """The config refuses N where the excluded set could approach N
    (rejection sampling would stall; the scores kernel owns that
    regime)."""
    with pytest.raises(ValueError):
        HeadlineExactConfig(n_nodes=64, fanout=4, ring0_size=0)
