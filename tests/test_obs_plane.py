"""Convergence observability plane: change provenance, broadcast-path
trace propagation, the always-on loop-health probe, and the cluster
measuring its own convergence (docs/telemetry.md).
"""

from __future__ import annotations

import asyncio
import time

import pytest

from corrosion_tpu.agent import tracing
from corrosion_tpu.agent.testing import (
    launch_test_agent,
    make_offline_agent,
    wait_for,
)
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import ActorId, ChangeSource, ChangeV1, Changeset
from corrosion_tpu.types.base import CrsqlSeq, Version


def _full_changeset(agent, version: int, db_version: int) -> ChangeV1:
    changes = agent.storage.collect_changes((db_version, db_version))
    last_seq = max(len(changes) - 1, 0)
    return ChangeV1(
        actor_id=ActorId(agent.actor_id),
        changeset=Changeset.full(
            Version(version), changes,
            (CrsqlSeq(0), CrsqlSeq(last_seq)), CrsqlSeq(last_seq),
            agent.clock.new_timestamp(),
        ),
    )


def _write(agent, i: int):
    return agent.execute_transaction(
        [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"t{i}"))]
    )


TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


# -- change provenance -------------------------------------------------


def test_provenance_records_first_arrival_per_path(tmp_path):
    """First arrival of each (actor, version) records lag under the
    arrival path's label; duplicates never re-count (first-seen
    dedupe); the origin actor's staleness gauge refreshes."""
    (tmp_path / "a1").mkdir()
    (tmp_path / "a2").mkdir()
    a1 = make_offline_agent(tmpdir=str(tmp_path / "a1"))
    a2 = make_offline_agent(tmpdir=str(tmp_path / "a2"))
    try:
        for i in range(3):
            _write(a1, i)
        cvs = [_full_changeset(a1, v, v) for v in (1, 2, 3)]
        # sync arrival
        assert a2.handle_change(cvs[0], ChangeSource.SYNC)
        # broadcast arrival: origin's own transmission (hop 0)
        assert a2.handle_change(
            cvs[1], ChangeSource.BROADCAST, meta=(TP, 0)
        )
        # rebroadcast arrival: relayed (hop > 0)
        assert a2.handle_change(
            cvs[2], ChangeSource.BROADCAST, meta=(TP, 2)
        )
        for path in ("sync", "broadcast", "rebroadcast"):
            count, total = a2.metrics.histogram_stats(
                "corro_change_lag_seconds", path=path
            )
            assert count == 1, path
            assert total >= 0.0
        # first-seen dedupe: a re-record of an already-seen version is
        # a no-op (later partial chunks / re-serves are not arrivals)
        a2._record_provenance(cvs[0], ChangeSource.SYNC, None)
        assert a2.metrics.histogram_stats(
            "corro_change_lag_seconds", path="sync"
        )[0] == 1
        # staleness gauge rides the scrape extras, labeled by origin
        stale = {
            labels["actor_id"]: v
            for name, v, labels in a2.metric_gauges()
            if name == "corro_change_staleness_seconds"
        }
        assert a1.actor_id.hex() in stale
        assert stale[a1.actor_id.hex()] >= 0.0
    finally:
        a1.storage.close()
        a2.storage.close()


def test_staleness_evicts_departed_actor(tmp_path):
    """An origin actor idle past staleness_evict_s AND absent from the
    alive membership drops off the staleness gauge (and out of
    _origin_ts_wall) instead of leaving a permanently rising series —
    a departed or rejoin-renewed actor must not grow label cardinality
    forever; an alive member is never evicted (its rising staleness IS
    the alert); a fresh write re-creates the entry."""
    (tmp_path / "a1").mkdir()
    (tmp_path / "a2").mkdir()
    a1 = make_offline_agent(tmpdir=str(tmp_path / "a1"))
    a2 = make_offline_agent(
        tmpdir=str(tmp_path / "a2"), staleness_evict_s=0.2
    )
    try:
        _write(a1, 1)
        assert a2.handle_change(_full_changeset(a1, 1, 1), ChangeSource.SYNC)
        actor = a1.actor_id.hex()

        def stale_actors():
            return {
                labels["actor_id"]
                for name, _v, labels in a2.metric_gauges()
                if name == "corro_change_staleness_seconds"
            }

        assert actor in stale_actors()
        # while the actor is an ALIVE member, idleness never evicts —
        # a live-but-unconverged actor's rising staleness is the alert
        from corrosion_tpu.agent.members import MemberState
        a2.members.upsert(
            a1.actor_id, ("127.0.0.1", 1), MemberState.ALIVE, 1
        )
        time.sleep(0.25)
        assert actor in stale_actors()
        a2.members.remove(a1.actor_id)
        assert actor not in stale_actors()  # evicted by the scrape
        assert a2._origin_ts_wall == {}  # the sole entry is gone
        # health snapshot shares the eviction path
        assert actor not in a2.health_snapshot()["origin_staleness_s"]
        # a later write from the actor re-creates the entry
        _write(a1, 2)
        assert a2.handle_change(_full_changeset(a1, 2, 2), ChangeSource.SYNC)
        assert actor in stale_actors()
        # evict=0 disables: entries stick around
        a2.config.staleness_evict_s = 0.0
        time.sleep(0.25)
        assert actor in stale_actors()
    finally:
        a1.storage.close()
        a2.storage.close()


def test_provenance_disabled_records_nothing(tmp_path):
    (tmp_path / "a1").mkdir()
    (tmp_path / "a2").mkdir()
    a1 = make_offline_agent(tmpdir=str(tmp_path / "a1"))
    a2 = make_offline_agent(tmpdir=str(tmp_path / "a2"), provenance=False)
    try:
        _write(a1, 1)
        assert a2.handle_change(_full_changeset(a1, 1, 1), ChangeSource.SYNC)
        assert a2.metrics.histogram_samples("corro_change_lag_seconds") == {}
        assert not any(
            name == "corro_change_staleness_seconds"
            for name, _v, _l in a2.metric_gauges()
        )
    finally:
        a1.storage.close()
        a2.storage.close()


# -- broadcast-path trace propagation + wire compat --------------------


def test_broadcast_frame_backward_compat(tmp_path):
    """Migration contract, mirroring PR 3's partial-buffer versioning:
    with propagation OFF the frame is byte-exact legacy; old-format
    payloads decode unchanged on a new receiver; traced frames carry
    (traceparent, hop) through to the receiver's decode."""
    from corrosion_tpu.types.actor import ClusterId
    from corrosion_tpu.types.payload import BroadcastV1, UniPayload

    (tmp_path / "old").mkdir()
    (tmp_path / "new").mkdir()
    old = make_offline_agent(
        tmpdir=str(tmp_path / "old"), bcast_trace_propagation=False
    )
    new = make_offline_agent(tmpdir=str(tmp_path / "new"))
    try:
        _write(old, 1)
        cv = _full_changeset(old, 1, 1)
        legacy_frame = old.encode_broadcast_frame(cv, hop=0)
        # byte-exact legacy wire output with propagation off
        assert legacy_frame == speedy.frame(
            speedy.encode_uni_payload(
                UniPayload(
                    broadcast=BroadcastV1(change=cv),
                    cluster_id=ClusterId(old.config.cluster_id),
                )
            )
        )
        payloads, rest = speedy.deframe(legacy_frame)
        assert rest == b""
        got = new.decode_uni_frame_meta(payloads[0])
        assert got is not None
        got_cv, tp, hop, sig = got
        assert got_cv == cv and tp is None and hop == 0 and sig is None
        # traced frame: the envelope rides ahead of the classic bytes
        _write(new, 2)
        cv2 = _full_changeset(new, 1, 2)
        traced_frame = new.encode_broadcast_frame(cv2, hop=1, traceparent=TP)
        payloads, _ = speedy.deframe(traced_frame)
        got_cv, tp, hop, _sig = new.decode_uni_frame_meta(payloads[0])
        assert got_cv == cv2 and tp == TP and hop == 1
        # ...and an old-config receiver still accepts it (decode is
        # format-agnostic; only EMISSION is gated)
        got_cv, tp, hop, _sig = old.decode_uni_frame_meta(payloads[0])
        assert got_cv == cv2 and tp == TP and hop == 1
    finally:
        old.storage.close()
        new.storage.close()


def test_enqueue_uni_payload_screens_both_formats(tmp_path):
    """The event-loop-side 12-byte tag prelude screen walks the traced
    envelope with offset arithmetic only — valid payloads of either
    format enqueue; junk (either layer) counts a decode error."""
    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        _write(a, 1)
        cv = _full_changeset(a, 1, 1)
        classic = speedy.deframe(
            a.encode_broadcast_frame(cv, 0, None)
        )[0][0]
        traced = speedy.deframe(
            a.encode_broadcast_frame(cv, 1, TP)
        )[0][0]
        assert traced[0] == speedy.TRACED_UNI_VERSION
        base = len(a._ingest)
        a.enqueue_uni_payload(classic)
        a.enqueue_uni_payload(traced)
        assert len(a._ingest) == base + 2
        errs0 = a.metrics.get_counter("corro_wire_decode_errors_total")
        a.enqueue_uni_payload(b"\x07garbage-envelope")
        a.enqueue_uni_payload(b"\x01\x00\x02bad-option-tag")
        a.enqueue_uni_payload(b"\x01\x00\x00" + b"junk-inner-payload!!")
        assert len(a._ingest) == base + 2  # none of the junk enqueued
        assert (
            a.metrics.get_counter("corro_wire_decode_errors_total")
            == errs0 + 3
        )
    finally:
        a.storage.close()


def test_write_group_trace_reaches_remote_apply(tmp_path):
    """One local write → one cross-cluster trace: write.group (origin)
    → bcast.collect (origin worker) → bcast.apply (remote first
    arrival) share a single trace id."""
    async def main():
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        # sync pushed out of the window: anti-entropy racing the
        # broadcast can deliver the version FIRST (path=sync, no
        # bcast.apply span), which is correct provenance but not the
        # path under test
        slow_sync = dict(sync_interval_min=30.0, sync_interval_max=60.0)
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"), **slow_sync)
        b = await launch_test_agent(
            tmpdir=str(tmp_path / "b"),
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
            **slow_sync,
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # the span ring is process-wide: a complete write trace
            # left by an EARLIER test must not satisfy the wait
            pre = {
                s.trace_id
                for s in tracing.recent_spans(tracing.RECENT_MAX)
                if s.name == "write.group"
            }
            _write(a, 501)

            def full_trace():
                for s in reversed(tracing.recent_spans(tracing.RECENT_MAX)):
                    if s.name == "write.group" and s.trace_id not in pre:
                        names = {
                            x.name
                            for x in tracing.recent_spans(
                                tracing.RECENT_MAX, trace_id=s.trace_id
                            )
                        }
                        if {"write.group", "bcast.collect",
                                "bcast.apply"} <= names:
                            return s.trace_id
                return None

            tid = await wait_for(full_trace, timeout=30)
            spans = tracing.recent_spans(tracing.RECENT_MAX, trace_id=tid)
            by_name = {s.name: s for s in spans}
            # parentage chain: group roots, collect parents on group,
            # apply parents on collect
            group = by_name["write.group"]
            collect = by_name["bcast.collect"]
            apply_ = by_name["bcast.apply"]
            assert group.parent_id is None
            assert collect.parent_id == group.span_id
            assert apply_.parent_id == collect.span_id
            # b's provenance recorded the same arrival
            count, _ = b.metrics.histogram_stats(
                "corro_change_lag_seconds", path="broadcast"
            )
            assert count >= 1
        finally:
            await b.stop()
            await a.stop()

    asyncio.run(main())


# -- always-on loop health probe ---------------------------------------


def test_stall_probe_attributes_slow_callbacks():
    """The probe measures scheduling gaps on the loop and the watchdog
    thread attributes a stall to the innermost in-package frame holding
    the loop (the probe coroutine can't see its own starvation)."""
    from corrosion_tpu.agent.health import LoopHealthProbe
    from corrosion_tpu.agent.metrics import Metrics

    # a stalling callback whose frame claims an in-package module — the
    # attribution walks f_globals["__name__"], so exec into a namespace
    # that looks like corrosion_tpu code
    g = {"__name__": "corrosion_tpu.test_glue", "time": time}
    exec("def stall(ms):\n    time.sleep(ms / 1000.0)\n", g)
    stall = g["stall"]

    async def main():
        m = Metrics()
        probe = LoopHealthProbe(m, interval=0.01, slow_ms=30.0)
        task = asyncio.create_task(probe.run())
        try:
            await asyncio.sleep(0.05)  # a few clean samples first
            asyncio.get_running_loop().call_soon(stall, 150)
            await asyncio.sleep(0.3)
            assert probe.samples > 0
            assert probe.max_stall_ms >= 100.0
            count, total = m.histogram_stats("corro_loop_stall_ms")
            assert count == probe.samples and total >= probe.max_stall_ms
            assert (
                m.get_counter_sum("corro_loop_slow_callbacks_total") >= 1
            )
            assert any(
                site.startswith("corrosion_tpu.test_glue:stall")
                for site in probe.slow_sites
            ), probe.slow_sites
            snap = probe.snapshot()
            assert snap["max_stall_ms"] >= 100.0
            assert snap["slow_sites"]
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    asyncio.run(main())


def test_health_surface_live_agent(tmp_path):
    """The agent runs the probe by default, exposes the stall series in
    /metrics, and serves the `health` admin command; `trace spans
    --trace` filters the ring to one trace."""
    async def main():
        import asyncio as aio

        sock = str(tmp_path / "admin.sock")
        a = await launch_test_agent(tmpdir=str(tmp_path), admin_path=sock)
        try:
            await wait_for(
                lambda: a.health is not None and a.health.samples > 0
            )
            _write(a, 601)
            snap = a.health_snapshot()
            assert snap["actor"] == a.actor_id.hex()
            assert snap["loop"]["samples"] > 0
            assert set(snap["queues"]) == {"changes", "bcast", "write"}
            from corrosion_tpu.agent.metrics import parse_prometheus_text

            fams = parse_prometheus_text(a.metrics.render(a.metric_gauges()))
            assert fams["corro_loop_stall_ms"]["samples"]
            assert fams["corro_loop_stall_max_ms"]["samples"]

            from corrosion_tpu.agent.admin import AdminClient

            with tracing.span("obs.marker") as marker:
                pass

            def call(cmd, **kw):
                c = AdminClient(sock)
                try:
                    return c.call(cmd, **kw)
                finally:
                    c.close()

            health = await aio.to_thread(call, "health")
            assert health["loop"]["samples"] > 0
            assert "convergence_lag" in health
            spans = await aio.to_thread(
                call, "trace_spans", limit=50, trace=marker.trace_id
            )
            assert spans and all(
                s["trace_id"] == marker.trace_id for s in spans
            )
            assert any(s["name"] == "obs.marker" for s in spans)
        finally:
            await a.stop()

    asyncio.run(main())


def test_stall_probe_disabled(tmp_path):
    async def main():
        a = await launch_test_agent(
            tmpdir=str(tmp_path), stall_probe_interval=0
        )
        try:
            assert a.health is None
            assert a.health_snapshot()["loop"] is None
        finally:
            await a.stop()

    asyncio.run(main())


# -- the cluster measuring itself --------------------------------------


def test_cluster_observer_self_measurement(tmp_path):
    """ClusterObserver: strict-parsed scrapes, pooled convergence lag,
    msgs/node, loop health, staleness — the cluster's own numbers."""
    from corrosion_tpu.devcluster import ClusterObserver

    async def main():
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"))
        b = await launch_test_agent(
            tmpdir=str(tmp_path / "b"),
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            obs = ClusterObserver({"a": a, "b": b})
            obs.mark()
            for i in range(3):
                _write(a, 700 + i)
            await wait_for(
                lambda: b.bookie.for_actor(
                    a.actor_id
                ).contains_version(3),
                timeout=15,
            )
            await wait_for(
                lambda: obs.convergence_lag()["count"] >= 3, timeout=15
            )
            lag = obs.convergence_lag()
            assert lag["count"] >= 3
            assert lag["p99_s"] >= lag["p50_s"] >= 0.0
            assert sum(lag["paths"].values()) == lag["count"]
            scrape = obs.scrape()  # strict parse of every node
            assert obs.msgs_per_node(scrape) > 0
            health = obs.loop_health(scrape)
            assert health["max_stall_ms"] >= 0.0
            stale = obs.staleness(scrape)
            assert a.actor_id.hex() in stale
            snap = obs.snapshot()
            assert snap["n_nodes"] == 2
            assert snap["convergence_lag"]["count"] >= 3
        finally:
            await b.stop()
            await a.stop()

    asyncio.run(main())


def test_obs_soak_smoke(tmp_path):
    """Small-N tier-1 smoke of `bench.py --obs`: the cluster's
    telemetry-derived p99 convergence lag sits within tolerance of
    harness ground truth, next to the kernel prediction."""
    from corrosion_tpu.sim.obs import run_obs

    out = tmp_path / "OBS_SMOKE.json"
    result = asyncio.run(
        run_obs(
            n=5,
            writes=8,
            seeds=2,
            out_path=str(out),
            base_dir=str(tmp_path / "cluster"),
        )
    )
    assert "error" not in result, result.get("error")
    assert result["within_tolerance"] is True
    ag = result["agents"]
    assert ag["ground_truth"]["samples"] > 0
    assert ag["telemetry"]["lag"]["count"] > 0
    assert ag["telemetry"]["msgs_per_node"] > 0
    # the assembled broadcast-path trace of one write
    assert "write.group" in ag["trace"]["span_names"]
    # kernel prediction rides alongside
    assert result["sim"]["predicted_wall_p99_s"] is not None
    assert result["diff"]["kernel_predicted_wall_p99_s"] is not None
    assert out.exists()


@pytest.mark.slow
def test_obs_soak_n32(tmp_path):
    """The full OBS_N32 gate: N=32, telemetry within ±15% of ground
    truth (the committed artifact's contract)."""
    from corrosion_tpu.sim.obs import run_obs

    result = asyncio.run(
        run_obs(
            n=32,
            writes=40,
            out_path=str(tmp_path / "OBS_N32.json"),
            base_dir=str(tmp_path / "cluster"),
        )
    )
    assert "error" not in result, result.get("error")
    assert result["within_tolerance"] is True
    # 32 in-process agents share one CPU-bound container, so the absolute
    # stall magnitude is environment noise; gate only on pathological lockup
    # and on the probe actually measuring.
    lh = result["agents"]["telemetry"]["loop_health"]
    assert 0.0 < lh["max_stall_ms"] < 10_000.0
