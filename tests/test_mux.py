"""Single-connection channel multiplexing (agent/mux.py).

Pins the verdict's transport-parity contract: ONE cached TCP
connection per peer carries the uni broadcast channel AND concurrent
bi sync sessions (the reference's single-QUIC-connection shape), with
per-channel stats, abort-vs-EOF semantics, and the hashed lane spread.
"""

import asyncio

import pytest

from corrosion_tpu.agent.mux import LANES, lane_of
from corrosion_tpu.agent.testing import launch_test_agent, wait_for


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_lane_hash_is_stable_and_spreads():
    """The endpoint-choice hash (transport.rs:55-93 parity): stable
    values, full [0, LANES) range over many peers."""
    assert lane_of(("10.0.0.1", 8787)) == lane_of(("10.0.0.1", 8787))
    lanes = {lane_of(("10.0.0.1", p)) for p in range(2000, 2200)}
    assert lanes == set(range(LANES))
    assert lane_of(("10.0.0.1", 1), lanes=3) in (0, 1, 2)


def test_tombstones_evict_oldest_first_and_never_resurrect():
    """Closed-channel bookkeeping: eviction is oldest-first, the most
    RECENTLY closed ids always stay tombstoned (the old arbitrary
    list(set)[:N] eviction could discard them and resurrect ghost
    sessions from late in-flight frames), and ids evicted from the set
    remain dead forever via the monotonic-id watermark."""
    from corrosion_tpu.agent.mux import TombstoneSet

    ts = TombstoneSet(cap=100)
    for ch in range(1000):
        ts.add(ch)
    # bounded memory
    assert len(ts) <= 100
    # the most recently closed ids are ALWAYS still tombstoned
    for ch in range(900, 1000):
        assert ch in ts, f"recently closed {ch} was resurrected"
    # evicted old ids stay dead via the watermark (never a ghost)
    for ch in (0, 1, 499, 899):
        assert ch in ts, f"evicted {ch} was resurrected"
    # a fresh id that never closed is not tombstoned
    assert 1000 not in ts
    # duplicate closes don't grow the structure
    before = len(ts)
    ts.add(999)
    ts.add(0)  # below the watermark: already dead, not re-added
    assert len(ts) == before
    # out-of-order closes around the watermark stay monotone-dead
    ts2 = TombstoneSet(cap=4)
    for ch in (5, 3, 9, 7, 11, 13):
        ts2.add(ch)
    assert all(ch in ts2 for ch in (3, 5, 7, 9, 11, 13))


def test_one_connection_carries_uni_and_sync(run):
    """Broadcast traffic AND a parallel sync round to the same peer
    ride ONE TCP connection: exactly one connect recorded, one cached
    mux, and both channel classes show bytes in the metrics."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # uni traffic: a write broadcasts b-ward
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (1, 'm')"]]
            )
            await wait_for(
                lambda: b.bookie.for_actor(a.actor_id).last() >= 1
            )
            # bi traffic: an explicit sync round b -> a
            await b.sync_round()

            b_addr = next(iter(b.transport.stats))
            assert len(b.transport._muxes) <= 1
            a_peer = next(iter(a.transport._muxes))
            st = a.transport.stats[a_peer]
            assert st.connects == 1, (
                "uni + sync must share one connection"
            )
            # per-channel stats: both classes flowed somewhere
            total_uni = a.metrics.get_counter(
                "corro_transport_bytes_total", channel="uni")
            total_bi = b.metrics.get_counter(
                "corro_transport_bytes_total", channel="bi")
            assert total_uni > 0
            assert total_bi > 0
            assert b.metrics.get_counter(
                "corro_transport_bi_channels_total") >= 1
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_concurrent_sync_sessions_multiplex(run):
    """Several sync sessions to the same peer run CONCURRENTLY over
    the one connection — distinct channels, no serialization through
    extra sockets."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            for i in range(5):
                a.execute_transaction(
                    [[f"INSERT INTO tests (id, text) VALUES ({i}, 'x')"]]
                )
            m = next(iter(b.members.alive()))
            counts = await asyncio.gather(
                *(b.parallel_sync([m]) for _ in range(4))
            )
            assert any(c >= 0 for c in counts)
            peer = next(iter(b.transport._muxes))
            assert b.transport.stats[peer].connects == 1
            # all five versions arrived through some session
            await wait_for(
                lambda: b.bookie.for_actor(a.actor_id).last() >= 5
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_abort_is_not_clean_eof(run):
    """A server-side channel abort surfaces as a connection error on
    the client's virtual reader — never as the clean EOF that would
    mark the sync session complete (the slow-peer-abort contract)."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            m = next(iter(b.members.alive()))
            reader, writer = await b.transport.open_bi(tuple(m.addr))
            # a garbage first frame makes _serve_sync error out; its
            # writer closes without ever sending State — the client
            # must see an exception or EOF-without-State, not a
            # completed handshake
            writer.write(b"\x00\x00\x00\x04junk")
            await writer.drain()
            writer.write_eof()
            got = b""
            try:
                while True:
                    chunk = await asyncio.wait_for(reader.read(4096), 5)
                    if not chunk:
                        break
                    got += chunk
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            # server never produced a sync State for garbage
            assert b"corro" not in got.lower()
            writer.close()
            # the shared connection SURVIVES a dead channel: a real
            # sync round immediately after still works on connect #1
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (7, 'ok')"]]
            )
            n = await b.parallel_sync([m])
            assert n >= 1
            peer = next(iter(b.transport._muxes))
            assert b.transport.stats[peer].connects == 1
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_hostile_frame_length_tears_connection(run):
    """A mux frame claiming more than the 8 MiB cap must tear the
    connection down instead of becoming a giant allocation."""
    async def main():
        import struct

        a = await launch_test_agent()
        try:
            r, w = await asyncio.open_connection(*a.gossip_addr)
            w.write(b"M" + struct.pack(">BII", 1, 1, 0xFFFFFFFF))
            await w.drain()
            # the server must close on us (no 4 GB read)
            data = await asyncio.wait_for(r.read(16), 5)
            assert data == b""
            w.close()
        finally:
            await a.stop()

    run(main())
