"""Churn calibration harness: the SWIM model's failure-detection
latency anchored against real agents (sim/churndiff.py)."""

import asyncio

import pytest

from corrosion_tpu.sim.churndiff import run_churndiff


def test_churndiff_small_cluster():
    """Detection and rejoin on real agents complete and land within a
    small multiple of the model's tick counts (the host pays a real
    probe-timeout chain the model folds into one tick)."""
    r = asyncio.run(run_churndiff(12, probe_interval=0.12))
    h, m, d = r["host"], r["model"], r["diff"]
    assert m["detect_ticks"] is not None
    assert h["detect_probe_periods"] > 0
    # loose, load-tolerant bounds: the model is an optimistic floor,
    # the host must not be an order of magnitude beyond it
    assert d["detect_ratio_host_over_model"] is not None
    assert 0.5 <= d["detect_ratio_host_over_model"] <= 6.0, d
    assert d["rejoin_ratio_host_over_model"] is not None
    assert 0.2 <= d["rejoin_ratio_host_over_model"] <= 8.0, d


def test_gossip_learned_suspicion_promotes_to_down():
    """A node that learns a SUSPECT record via gossip runs its own
    suspicion deadline (foca per-node timers): it promotes the member
    to DOWN without ever probing it itself."""
    from corrosion_tpu.agent.members import MemberState
    from corrosion_tpu.agent.testing import launch_test_agent, wait_for

    async def main():
        # observer with probing effectively OFF: it can only learn via
        # ingest, so the DOWN transition must come from its own timer
        a = await launch_test_agent(
            probe_interval=3600.0, suspect_timeout=0.3
        )
        try:
            from corrosion_tpu.bridge import foca
            from corrosion_tpu.agent import swim_foca

            peer = foca.FocaActor(
                id=b"\x77" * 16, addr=("127.0.0.1", 1), ts=5,
                cluster_id=0,
            )
            swim_foca._ingest_update(a, foca.FocaMember(
                actor=peer, incarnation=0, state=foca.STATE_SUSPECT,
            ))
            m = a.members.get(peer.id)
            assert m is not None and m.state is MemberState.SUSPECT
            assert peer.id in a._suspects  # local timer armed
            # age the timer past the deadline and run one reaper pass:
            # the gossip-learned suspicion promotes to DOWN without
            # this node ever probing the member
            import time as t

            a._suspects[peer.id] = (
                t.monotonic() - a._suspect_deadline() - 1.0
            )
            a._reap_suspects()
            m = a.members.get(peer.id)
            assert m is not None and m.state is MemberState.DOWN
            assert peer.id not in a._suspects
        finally:
            await a.stop()

    asyncio.run(main())
