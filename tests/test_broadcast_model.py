import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.broadcast import BroadcastParams, broadcast_step
from corrosion_tpu.ops.keys import DEFAULT_CODEC as C


def _init(n, r=4):
    base = C.pack(
        jnp.ones((n, r), jnp.int32),
        jnp.ones((n, r), jnp.int32),
        jnp.zeros((n, r), jnp.int32),
    )
    news = C.pack(
        jnp.ones((r,), jnp.int32),
        jnp.full((r,), 2, jnp.int32),
        jnp.ones((r,), jnp.int32),
    )
    rows = base.at[0].set(news)
    return rows, news


def test_lossless_epidemic_converges():
    n = 512
    p = BroadcastParams(n_nodes=n, fanout_ring0=2, fanout_global=2, ring0_size=64)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(p.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(0)
    for t in range(40):
        rows, tx, msgs, *_ = broadcast_step(rows, tx, msgs, jax.random.fold_in(key, t), p)
        if bool(jnp.all(rows == news[None, :])):
            break
    assert bool(jnp.all(rows == news[None, :])), "did not converge in 40 ticks"
    # epidemic should be fast: O(log N) plus decay tail
    assert t < 30


def test_messages_counted_only_for_active_senders():
    n = 8
    p = BroadcastParams(n_nodes=n, fanout_ring0=1, fanout_global=1, ring0_size=4)
    rows, _ = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(2)
    msgs = jnp.zeros((n,), jnp.int32)
    rows2, tx2, msgs2, *_ = broadcast_step(rows, tx, msgs, jax.random.PRNGKey(1), p)
    assert int(msgs2[0]) == p.fanout
    assert int(tx2[0]) == 1
    # quiescent nodes sent nothing (unless they just learned -> only recv)
    assert int(msgs2[1:].sum()) == 0


def test_retransmit_decay_quiesces():
    n = 16
    p = BroadcastParams(n_nodes=n, fanout_ring0=1, fanout_global=1, ring0_size=8,
                        max_transmissions=3)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(3)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(2)
    for t in range(64):
        rows, tx, msgs, *_ = broadcast_step(rows, tx, msgs, jax.random.fold_in(key, t), p)
    assert int(tx.max()) == 0, "all transmission budgets must eventually drain"
    total = int(msgs.sum())
    for t in range(64, 70):
        rows, tx, msgs, *_ = broadcast_step(rows, tx, msgs, jax.random.fold_in(key, t), p)
    assert int(msgs.sum()) == total, "quiescent cluster must stop sending"


def test_partition_blocks_cross_traffic():
    n = 64
    p = BroadcastParams(n_nodes=n, fanout_ring0=2, fanout_global=2, ring0_size=8)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(p.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)
    part = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    key = jax.random.PRNGKey(3)
    for t in range(50):
        rows, tx, msgs, *_ = broadcast_step(
            rows, tx, msgs, jax.random.fold_in(key, t), p,
            partition_id=part, partition_active=jnp.array(True),
        )
    has_news = np.asarray((rows == news[None, :]).all(axis=1))
    assert has_news[: n // 2].all(), "writer's side should converge"
    assert not has_news[n // 2 :].any(), "no message may cross the partition"


def test_loss_slows_but_does_not_stop():
    n = 256
    p = BroadcastParams(n_nodes=n, fanout_ring0=2, fanout_global=2, ring0_size=32,
                        loss=0.05, max_transmissions=8)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(p.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(4)
    for t in range(60):
        rows, tx, msgs, *_ = broadcast_step(rows, tx, msgs, jax.random.fold_in(key, t), p)
        if bool(jnp.all(rows == news[None, :])):
            break
    assert bool(jnp.all(rows == news[None, :]))


def test_prime_n_ring0_fallback_still_spreads():
    """A node count with no useful divisor <= ring0_size (e.g. prime)
    must not degenerate ring0 columns into self-sends: the sliding-
    window fallback keeps the tier delivering (_perm_senders)."""
    n = 97  # prime: largest divisor <= 16 is 1
    p = BroadcastParams(n_nodes=n, fanout_ring0=2, fanout_global=0,
                        ring0_size=16, max_transmissions=8)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(p.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(5)
    for t in range(8):
        rows, tx, msgs, *_ = broadcast_step(
            rows, tx, msgs, jax.random.fold_in(key, t), p)
    infected = int((rows == news[None, :]).all(axis=1).sum())
    assert infected > 1, "ring0-only fanout at prime n must still spread"


def test_active_sender_with_unset_hops_still_delivers():
    """The packed activity/hop field must not conflate 'uninfected' with
    'inactive': a sender granted tx budget while its hop depth is the
    HOP_UNSET sentinel (e.g. healed by sync, then rebroadcasting) still
    delivers; receivers record a clamped 'unknown depth'."""
    from corrosion_tpu.models.broadcast import HOP_UNSET

    n = 64
    p = BroadcastParams(n_nodes=n, fanout_ring0=0, fanout_global=3,
                        ring0_size=1, max_transmissions=4)
    rows, news = _init(n)
    tx = jnp.zeros((n,), jnp.int32).at[0].set(p.max_transmissions)
    msgs = jnp.zeros((n,), jnp.int32)
    hops = jnp.full((n,), HOP_UNSET, jnp.int32)  # writer's hop UNSET too
    key = jax.random.PRNGKey(6)
    for t in range(40):
        step = broadcast_step(
            rows, tx, msgs, jax.random.fold_in(key, t), p, hops=hops)
        rows, tx, msgs, hops = (
            step.rows, step.tx_remaining, step.msgs_sent, step.hops)
        if bool(jnp.all(rows == news[None, :])):
            break
    assert bool(jnp.all(rows == news[None, :])), "delivery must not stall"
