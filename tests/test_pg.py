"""PostgreSQL wire-protocol server tests (raw pgwire v3 client)."""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from tests.pgwire_client import PgClient


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_pg_handshake_and_simple_query(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                assert c.params.get("server_version") == "14.9"
                cols, rows, tags, errs = c.query("SELECT version()")
                assert not errs and "corrosion-tpu" in rows[0][0]
                cols, rows, tags, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (1, 'via pg')"
                )
                assert tags == ["INSERT 0 1"] and not errs
                cols, rows, tags, errs = c.query(
                    "SELECT id, text FROM tests"
                )
                assert cols == ["id", "text"]
                assert rows == [["1", "via pg"]]
                assert tags == ["SELECT 1"]
                c.close()

            await asyncio.to_thread(drive)
            # the PG write went through the versioned path
            assert a.bookie.for_actor(a.actor_id).last() == 1
        finally:
            await a.stop()

    run(main())


def test_pg_extended_protocol_params(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)", (5, "param")
                )
                assert err is None and tag == "INSERT 0 1"
                cols, rows, tag, err = c.prepared(
                    "SELECT text FROM tests WHERE id = $1", (5,)
                )
                assert err is None
                assert rows == [["param"]]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_transaction_groups_one_version(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                assert c.txn_status == "T"
                c.query("INSERT INTO tests (id, text) VALUES (1, 'a')")
                c.query("INSERT INTO tests (id, text) VALUES (2, 'b')")
                c.query("COMMIT")
                assert c.txn_status == "I"
                c.close()

            await asyncio.to_thread(drive)
            assert a.bookie.for_actor(a.actor_id).last() == 1  # one version
            n = a.storage.conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
            assert n == 2
        finally:
            await a.stop()

    run(main())


def test_pg_rollback_discards(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id) VALUES (9)")
                c.query("ROLLBACK")
                c.close()

            await asyncio.to_thread(drive)
            n = a.storage.conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
            assert n == 0
            assert a.bookie.for_actor(a.actor_id).last() == 0
        finally:
            await a.stop()

    run(main())


def test_pg_errors_and_multi_statement(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                _, _, _, errs = c.query("SELECT FROM no_such")
                assert errs, "bad SQL must produce an ErrorResponse"
                # connection still usable
                cols, rows, tags, errs = c.query(
                    "INSERT INTO tests (id) VALUES (1); SELECT COUNT(*) FROM tests"
                )
                assert not errs
                assert tags[-1] == "SELECT 1" and rows == [["1"]]
                # pg write gossips like any write
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_write_broadcasts_to_cluster(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())

            def drive():
                c = PgClient(*a.pg_addr)
                c.query("INSERT INTO tests (id, text) VALUES (3, 'pg-gossip')")
                c.close()

            await asyncio.to_thread(drive)
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT text FROM tests WHERE id=3"
                ).fetchone()
                == ("pg-gossip",)
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())
