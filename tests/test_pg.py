"""PostgreSQL wire-protocol server tests (raw pgwire v3 client)."""

import asyncio

import pytest

from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from tests.pgwire_client import PgClient


@pytest.fixture
def run():
    def _run(coro):
        return asyncio.run(coro)

    return _run


def test_pg_handshake_and_simple_query(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                assert c.params.get("server_version") == "14.9"
                cols, rows, tags, errs = c.query("SELECT version()")
                assert not errs and "corrosion-tpu" in rows[0][0]
                cols, rows, tags, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (1, 'via pg')"
                )
                assert tags == ["INSERT 0 1"] and not errs
                cols, rows, tags, errs = c.query(
                    "SELECT id, text FROM tests"
                )
                assert cols == ["id", "text"]
                assert rows == [["1", "via pg"]]
                assert tags == ["SELECT 1"]
                c.close()

            await asyncio.to_thread(drive)
            # the PG write went through the versioned path
            assert a.bookie.for_actor(a.actor_id).last() == 1
        finally:
            await a.stop()

    run(main())


def test_pg_extended_protocol_params(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)", (5, "param")
                )
                assert err is None and tag == "INSERT 0 1"
                cols, rows, tag, err = c.prepared(
                    "SELECT text FROM tests WHERE id = $1", (5,)
                )
                assert err is None
                assert rows == [["param"]]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_transaction_groups_one_version(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                assert c.txn_status == "T"
                c.query("INSERT INTO tests (id, text) VALUES (1, 'a')")
                c.query("INSERT INTO tests (id, text) VALUES (2, 'b')")
                c.query("COMMIT")
                assert c.txn_status == "I"
                c.close()

            await asyncio.to_thread(drive)
            assert a.bookie.for_actor(a.actor_id).last() == 1  # one version
            n = a.storage.conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
            assert n == 2
        finally:
            await a.stop()

    run(main())


def test_pg_rollback_discards(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id) VALUES (9)")
                c.query("ROLLBACK")
                c.close()

            await asyncio.to_thread(drive)
            n = a.storage.conn.execute("SELECT COUNT(*) FROM tests").fetchone()[0]
            assert n == 0
            assert a.bookie.for_actor(a.actor_id).last() == 0
        finally:
            await a.stop()

    run(main())


def test_pg_errors_and_multi_statement(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                _, _, _, errs = c.query("SELECT FROM no_such")
                assert errs, "bad SQL must produce an ErrorResponse"
                # connection still usable
                cols, rows, tags, errs = c.query(
                    "INSERT INTO tests (id) VALUES (1); SELECT COUNT(*) FROM tests"
                )
                assert not errs
                assert tags[-1] == "SELECT 1" and rows == [["1"]]
                # pg write gossips like any write
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_write_broadcasts_to_cluster(run):
    async def main():
        a = await launch_test_agent(pg_port=0)
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())

            def drive():
                c = PgClient(*a.pg_addr)
                c.query("INSERT INTO tests (id, text) VALUES (3, 'pg-gossip')")
                c.close()

            await asyncio.to_thread(drive)
            await wait_for(
                lambda: b.storage.conn.execute(
                    "SELECT text FROM tests WHERE id=3"
                ).fetchone()
                == ("pg-gossip",)
            )
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_pg_typed_params_and_results(run):
    """Declared OIDs bind natively (text and binary format) and result
    columns carry inferred OIDs a typed driver decodes back — the
    round-trip a stock psycopg would do (no PG driver in this image, so
    the raw-wire client plays its part)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                INT8, TEXT = 20, 25
                c = PgClient(*a.pg_addr)
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)",
                    (7, "typed"), param_oids=(INT8, TEXT),
                )
                assert err is None and tag == "INSERT 0 1"
                # binary-format params (psycopg's int binding)
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)",
                    (8, "binary"), param_oids=(INT8, TEXT), binary=True,
                )
                assert err is None and tag == "INSERT 0 1"
                # typed results: ints come back as ints
                cols, rows, tag, err = c.typed_query(
                    "SELECT id, text FROM tests ORDER BY id"
                )
                assert err is None
                assert rows == [(7, "typed"), (8, "binary")]
                assert c.col_oids == [INT8, TEXT]
                c.close()

            await asyncio.to_thread(drive)
            # the stored values are native sqlite INTEGERs, not text
            _, rows = a.storage.read_query(
                "SELECT typeof(id), typeof(text) FROM tests"
            )
            assert rows == [("integer", "text")] * 2
        finally:
            await a.stop()

    run(main())


def test_pg_and_http_writes_merge_identically(run):
    """The golden divergence case: the same logical write through the
    PG wire and through HTTP must produce byte-identical CRDT state, so
    LWW ties resolve the same on every node."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        b = await launch_test_agent(
            pg_port=0,
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # HTTP write on a
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [100, "h"]]
            ])
            # the same-shape write via PG on b (typed params)
            def drive():
                c = PgClient(*b.pg_addr)
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)",
                    (200, "p"), param_oids=(20, 25),
                )
                assert err is None and tag == "INSERT 0 1"
                c.close()

            await asyncio.to_thread(drive)

            def table(x):
                return x.storage.read_query(
                    "SELECT id, text, typeof(id) FROM tests ORDER BY id"
                )[1]

            await wait_for(
                lambda: table(a) == table(b) and len(table(a)) == 2,
                timeout=15,
            )
            assert table(a) == [
                (100, "h", "integer"), (200, "p", "integer")
            ]
        finally:
            await b.stop()
            await a.stop()

    run(main())


def test_pg_catalog_is_queryable(run):
    """Real catalog SQL (the joins \\d-style tooling runs) works against
    the rendered pg_catalog, and information_schema lists columns."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                cols, rows, _, errs = c.query(
                    "SELECT c.relname, a.attname, t.typname"
                    " FROM pg_catalog.pg_class c"
                    " JOIN pg_catalog.pg_attribute a ON a.attrelid = c.oid"
                    " JOIN pg_catalog.pg_type t ON t.oid = a.atttypid"
                    " WHERE c.relnamespace = 2200"
                    " ORDER BY c.relname, a.attnum"
                )
                assert not errs
                assert ["tests", "id", "int8"] in rows
                assert ["tests", "text", "text"] in rows
                cols, rows, _, errs = c.query(
                    "SELECT table_name, column_name, data_type"
                    " FROM information_schema.columns"
                    " WHERE table_name = 'tests2' ORDER BY ordinal_position"
                )
                assert not errs
                assert rows == [
                    ["tests2", "id", "int8"], ["tests2", "text", "text"]
                ]
                # driver-startup probes: database list + identity funcs
                _, rows, _, errs = c.query(
                    "SELECT datname FROM pg_catalog.pg_database"
                    " WHERE datallowconn = 1"
                )
                assert not errs and rows == [["corrosion"]]
                _, rows, _, errs = c.query("SELECT current_database()")
                assert not errs and rows == [["corrosion"]]
                _, rows, _, errs = c.query("SELECT current_schema()")
                assert not errs and rows == [["public"]]
                # unqualified catalog names + expression contexts, the
                # forms real driver/ORM startups actually send
                _, rows, _, errs = c.query(
                    "SELECT datname FROM pg_database WHERE datallowconn = 1"
                )
                assert not errs and rows == [["corrosion"]]
                _, rows, _, errs = c.query(
                    "SELECT current_database() AS name, current_schema()"
                )
                assert not errs and rows == [["corrosion", "public"]]
                # comma-style from-list still routes to the catalog
                _, rows, _, errs = c.query(
                    "SELECT t.typname FROM pg_class c, pg_type t"
                    " WHERE t.oid = 20 LIMIT 1"
                )
                assert not errs and rows and rows[0] == ["int8"]
                # a user COLUMN merely named pg_class must not reroute
                # the query to the rendered catalog (ADVICE r3)
                c.query("INSERT INTO tests (id, text)"
                        " VALUES (1, 'pg_class ref')")
                _, rows, _, errs = c.query(
                    "SELECT text AS pg_class FROM tests WHERE id = 1"
                )
                assert not errs and rows == [["pg_class ref"]]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_unqualified_catalog_table_position_only():
    """Unqualified catalog routing keys on genuine table position:
    FROM/JOIN items (incl. old-style comma joins), never select-list or
    ORDER BY identifiers that merely share a pg_* name (ADVICE r3)."""
    from corrosion_tpu.agent.pg import _unqualified_catalog_table as f

    assert f("select * from foo, pg_class") == "pg_class"
    assert f('select * from "pg_class"') == "pg_class"
    assert f("select a.attname from foo f join pg_attribute a"
             " on a.x = f.x") == "pg_attribute"
    assert f("select c.relname from pg_class c, pg_type t"
             " where t.oid = c.oid") == "pg_class"
    # subqueries: an inner WHERE must not hide later from-list items,
    # and catalog refs inside subqueries are still found
    assert f("select s.x, c.relname from (select 1 as x from t"
             " where t.id > 0) s, pg_class c") == "pg_class"
    assert f("select x from (select relname as x from pg_class) q"
             ) == "pg_class"
    # pg_* names OUTSIDE table position must not reroute
    assert f("select id, pg_type from readings") is None
    assert f("select id from tests order by id, pg_index") is None
    assert f("select pg_class from tests where id in (1, 2)") is None
    assert f("select id from tests group by id, pg_range") is None


def test_pg_bind_error_discards_until_sync(run):
    """A failed Bind must not leave the previous portal bound: the
    pipelined Execute that follows is discarded until Sync instead of
    silently re-running the old statement (duplicate INSERT)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                import struct as st

                c = PgClient(*a.pg_addr)
                # a successful prepared INSERT leaves portal '' bound
                _, _, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)",
                    (1, "once"), param_oids=(20, 25),
                )
                assert err is None and tag == "INSERT 0 1"
                # now a Bind that fails to decode (binary date OID),
                # pipelined with an Execute + Sync
                parse = b"\x00" + b"INSERT INTO tests (id, text) VALUES ($1, 'x')\x00"
                parse += st.pack(">h", 1) + st.pack(">I", 1082)  # date OID
                c._send(b"P", parse)
                bind = b"\x00\x00" + st.pack(">hh", 1, 1)  # binary fmt
                bind += st.pack(">h", 1) + st.pack(">i", 4) + st.pack(">i", 123)
                bind += st.pack(">h", 0)
                c._send(b"B", bind)
                c._send(b"E", b"\x00" + st.pack(">i", 0))
                c._send(b"S")
                saw_error = False
                for tag_, payload in c._messages_until(b"Z"):
                    if tag_ == b"E":
                        saw_error = True
                assert saw_error
                c.close()

            await asyncio.to_thread(drive)
            # exactly ONE row: the discarded Execute did not re-run the
            # old INSERT, and the failed one never ran
            _, rows = a.storage.read_query("SELECT count(*) FROM tests")
            assert rows == [(1,)]
        finally:
            await a.stop()

    run(main())


def test_pg_stop_aborts_idle_sessions(run):
    """Agent.stop() must not hang while a pgwire client sits idle on an
    open session: wait_closed() waits for every handler, so shutdown
    aborts live connections (the reference's tripwire teardown)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        c = None
        try:
            def connect():
                cl = PgClient(*a.pg_addr)
                cl.query("SELECT 1")
                return cl
            c = await asyncio.to_thread(connect)
        finally:
            # the client is never closed: stop() must still return
            await asyncio.wait_for(a.stop(), timeout=10)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    run(main())


def test_pg_tls_handshake_and_query(run, tmp_path):
    """SSLRequest is answered 'S' when the agent has TLS configured and
    the whole session (startup, writes, reads) runs over the encrypted
    stream (corro-pg TLS parity)."""
    from corrosion_tpu.agent.tls import generate_ca, generate_server_cert

    d = str(tmp_path)
    ca_cert, ca_key = generate_ca(d)
    srv_cert, srv_key = generate_server_cert(
        d, ca_cert, ca_key, ["127.0.0.1", "localhost"]
    )

    async def main():
        a = await launch_test_agent(
            pg_port=0, tls_cert_file=srv_cert, tls_key_file=srv_key,
            tls_ca_file=ca_cert,
        )
        try:
            def drive():
                c = PgClient(*a.pg_addr, tls=True, ca_file=ca_cert)
                _, _, tags, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (5, 'tls')"
                )
                assert not errs and tags == ["INSERT 0 1"]
                _, rows, _, errs = c.query(
                    "SELECT text FROM tests WHERE id = 5"
                )
                assert not errs and rows == [["tls"]]
                c.close()
                # a client that skips SSLRequest entirely still works
                c2 = PgClient(*a.pg_addr)
                _, rows, _, errs = c2.query(
                    "SELECT text FROM tests WHERE id = 5"
                )
                assert not errs and rows == [["tls"]]
                c2.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_portal_suspension(run):
    """Execute with a row limit drains the portal in chunks:
    PortalSuspended after each partial round, CommandComplete at the
    end, no duplicate RowDescription (corro-pg portal max-row
    suspension)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            a.execute_transaction([
                ["INSERT INTO tests (id, text) VALUES (?, ?)",
                 [i, f"r{i}"]]
                for i in range(10)
            ])

            def drive():
                c = PgClient(*a.pg_addr)
                rounds, suspensions, tag, err = c.execute_limited(
                    "SELECT id FROM tests ORDER BY id", max_rows=3
                )
                assert err is None
                assert rounds == [3, 3, 3, 1]
                assert suspensions == 3
                assert tag == "SELECT 10"
                # session still healthy afterwards
                _, rows, _, errs = c.query("SELECT count(*) FROM tests")
                assert not errs and rows == [["10"]]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_tokenizer_translation():
    """The tokenizer pass never rewrites inside literals/identifiers
    and handles the PG-isms the regex pass could not."""
    from corrosion_tpu.agent.pgsql import split_statements, translate_query

    t = lambda s: translate_query(s)[0]
    # $N params with order, repeated and out-of-order
    sql, order = translate_query(
        "SELECT * FROM t WHERE a = $2 AND b = $1 AND c = $2")
    assert sql == "SELECT * FROM t WHERE a = ? AND b = ? AND c = ?"
    assert order == [2, 1, 2]
    # casts dropped, incl. array casts — but never inside strings
    assert t("SELECT x::int8, '::text literal'") == (
        "SELECT x, '::text literal'")
    assert t("SELECT y::text[] FROM t") == "SELECT y FROM t"
    # function mapping only on real call sites / bare keywords
    assert t("SELECT now()") == "SELECT datetime('now')"
    assert t("SELECT current_timestamp") == "SELECT datetime('now')"
    assert t("SELECT 'now()' AS s") == "SELECT 'now()' AS s"
    assert t('SELECT "current_timestamp" FROM t') == (
        'SELECT "current_timestamp" FROM t')
    # E-strings decode; dollar-quotes become standard literals
    assert t(r"SELECT E'a\nb'") == "SELECT 'a\nb'"
    assert t("SELECT $tag$it's here$tag$") == "SELECT 'it''s here'"
    # ILIKE maps; the word inside an identifier does not
    assert t("SELECT * FROM t WHERE a ILIKE 'x%'") == (
        "SELECT * FROM t WHERE a LIKE 'x%'")
    # comments stripped, even with semicolons inside
    assert split_statements(
        "SELECT 1; -- trailing; comment\nSELECT 2"
    )[1].strip().startswith("SELECT 2")
    assert t("SELECT /* c1 ; */ 1").split() == ["SELECT", "1"]
    # multi-word / parenthesized / quoted type names vanish whole
    assert t("SELECT x::double precision FROM t") == "SELECT x FROM t"
    assert t("SELECT x::numeric(10,2) FROM t") == "SELECT x FROM t"
    assert t("SELECT x::character varying(20) FROM t") == "SELECT x FROM t"
    assert t("SELECT x::timestamp with time zone FROM t") == (
        "SELECT x FROM t")
    assert t("SELECT x::time(3) without time zone FROM t") == (
        "SELECT x FROM t")
    assert t('SELECT x::"SomeType" FROM t') == "SELECT x FROM t"
    # schema-qualified type names vanish whole too (pg_dump/ORM shape)
    assert t("SELECT x::pg_catalog.int4 FROM t") == "SELECT x FROM t"
    assert t('SELECT x::myschema."MyType"[] FROM t') == "SELECT x FROM t"
    # ...but bare words that merely FOLLOW a cast survive
    assert t("SELECT x::int zone FROM t") == "SELECT x zone FROM t"


def test_pg_is_write_classification():
    """WITH-led statements: DML heads after the last CTE body are
    writes; a write-word used as a function call is not (the round-4
    advisor's replace() case)."""
    from corrosion_tpu.agent.pg import _is_write

    assert _is_write("INSERT INTO t VALUES (1)")
    assert _is_write("WITH x AS (SELECT 1) INSERT INTO t SELECT * FROM x")
    assert _is_write("WITH x AS (SELECT 1), y AS (SELECT 2) DELETE FROM t")
    assert _is_write("with q as (select 1) update t set a = 1")
    assert not _is_write("SELECT 1")
    assert not _is_write("WITH x AS (SELECT 1) SELECT * FROM x")
    assert not _is_write(
        "WITH x AS (SELECT 1) SELECT replace(a, '1', '2') FROM t"
    )
    # REPLACE as a bare column alias (not reserved in PG) is not DML
    assert not _is_write("WITH x AS (SELECT 1) SELECT (a + b) replace FROM t")



def test_pg_estring_unicode_and_octal_escapes():
    from corrosion_tpu.agent.pgsql import translate_query

    t = lambda s: translate_query(s)[0]
    assert t(r"SELECT E'\u00e9'") == "SELECT 'é'"
    assert t(r"SELECT E'\U0001F600'") == "SELECT '\U0001F600'"
    assert t(r"SELECT E'\101\102'") == "SELECT 'AB'"
    assert t(r"SELECT E'\x41'") == "SELECT 'A'"


def test_pg_insert_returning(run):
    """INSERT ... RETURNING flows through the versioned write path and
    returns the produced rows (the ORM write shape), on both the simple
    and the extended protocol; the write still broadcasts."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                cols, rows, tags, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (11, 'r')"
                    " RETURNING id, text"
                )
                assert not errs, errs
                assert cols == ["id", "text"] and rows == [["11", "r"]]
                assert tags == ["INSERT 0 1"]
                # extended protocol with a parameter
                cols, rows, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)"
                    " RETURNING id", (12, "s"),
                )
                assert err is None and rows == [["12"]], (rows, err)
                # UPDATE ... RETURNING
                cols, rows, tags, errs = c.query(
                    "UPDATE tests SET text = 'up' WHERE id = 11"
                    " RETURNING text"
                )
                assert not errs and rows == [["up"]]
                c.close()

            await asyncio.to_thread(drive)
            # versioned: both writes allocated versions
            assert a.bookie.for_actor(a.actor_id).last() == 3
        finally:
            await a.stop()

    run(main())


def test_pg_returning_describe_and_txn_limits(run):
    """RETURNING edges: Describe announces the row shape before Execute
    (drivers choose their fetch path from it), and RETURNING inside an
    explicit transaction fails fast instead of silently dropping rows
    (writes buffer until COMMIT)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                from corrosion_tpu.agent.pg import _returning_columns

                assert _returning_columns(
                    "INSERT INTO tests (id) VALUES (1) RETURNING id, text",
                    a,
                ) == ["id", "text"]
                assert _returning_columns(
                    "UPDATE tests SET text='x' RETURNING *", a
                ) == ["id", "text"]
                assert _returning_columns(
                    "DELETE FROM tests RETURNING id AS gone", a
                ) == ["gone"]
                assert _returning_columns(
                    "INSERT INTO tests (id) VALUES (1)", a) is None
                assert _returning_columns(
                    "INSERT INTO tests (text) VALUES ('RETURNING x')", a
                ) is None

                c = PgClient(*a.pg_addr)
                # extended protocol: the T frame arrives at Describe
                # time and Execute returns the row
                cols, rows, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)"
                    " RETURNING id", (21, "d"),
                )
                assert err is None and cols == ["id"] and rows == [["21"]]
                # explicit txn: fail fast
                c.query("BEGIN")
                _, _, _, errs = c.query(
                    "INSERT INTO tests (id) VALUES (22) RETURNING id"
                )
                assert errs and "RETURNING" in errs[0]
                c.query("ROLLBACK")
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_returning_edge_shapes(run):
    """RETURNING column derivation: declaration-order * expansion,
    quoted table names, function calls with internal commas, and a
    correct rows_affected count."""
    async def main():
        schema = (
            "CREATE TABLE IF NOT EXISTS oddpk ("
            " name TEXT NOT NULL DEFAULT '', id INTEGER NOT NULL"
            " PRIMARY KEY);"
        )
        from corrosion_tpu.agent.testing import TEST_SCHEMA

        a = await launch_test_agent(pg_port=0, schema=TEST_SCHEMA + schema)
        try:
            from corrosion_tpu.agent.pg import _returning_columns

            # declaration order, not pk-first
            assert _returning_columns(
                "INSERT INTO oddpk (id) VALUES (1) RETURNING *", a
            ) == ["name", "id"]
            assert _returning_columns(
                'INSERT INTO "oddpk" (id) VALUES (1) RETURNING *', a
            ) == ["name", "id"]
            # comma inside a function call is not a separator
            assert _returning_columns(
                "INSERT INTO tests (id) VALUES (1)"
                " RETURNING coalesce(id, 0), text", a
            ) == ["id", "text"]

            def drive():
                c = PgClient(*a.pg_addr)
                # Describe columns match the Execute rows for *
                cols, rows, tag, err = c.prepared(
                    "INSERT INTO oddpk (name, id) VALUES ($1, $2)"
                    " RETURNING *", ("n1", 41),
                )
                assert err is None and cols == ["name", "id"]
                assert rows == [["n1", "41"]]
                # rows_affected counts fetched RETURNING rows
                cols, rows, tags, errs = c.query(
                    "UPDATE oddpk SET name = 'x' RETURNING id"
                )
                assert not errs and tags == ["UPDATE 1"], (tags, errs)
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_transaction_read_your_writes(run):
    """Reads inside BEGIN..COMMIT see the transaction's own buffered
    writes (READ COMMITTED read-your-writes, the ORM
    insert-then-select shape), other sessions see nothing until
    COMMIT, and ROLLBACK leaves no trace."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c2 = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (1, 'mine')")
                # same session sees the pending write
                _, rows, _, errs = c.query(
                    "SELECT text FROM tests WHERE id = 1"
                )
                assert not errs and rows == [["mine"]], (rows, errs)
                # an UPDATE of the pending row is visible too
                c.query("UPDATE tests SET text = 'mine2' WHERE id = 1")
                _, rows, _, errs = c.query(
                    "SELECT text FROM tests WHERE id = 1"
                )
                assert not errs and rows == [["mine2"]]
                # other sessions see committed state only
                _, rows2, _, _ = c2.query("SELECT count(*) FROM tests")
                assert rows2 == [["0"]]
                c.query("ROLLBACK")
                _, rows, _, _ = c.query("SELECT count(*) FROM tests")
                assert rows == [["0"]]
                # commit path: durable + single version
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (2, 'kept')")
                _, rows, _, _ = c.query(
                    "SELECT text FROM tests WHERE id = 2"
                )
                assert rows == [["kept"]]
                c.query("COMMIT")
                _, rows2, _, _ = c2.query(
                    "SELECT text FROM tests WHERE id = 2"
                )
                assert rows2 == [["kept"]]
                c.close()
                c2.close()

            await asyncio.to_thread(drive)
            assert a.bookie.for_actor(a.actor_id).last() == 1
        finally:
            await a.stop()

    run(main())


def test_pg_pragma_in_txn_stays_off_write_conn(run):
    """A PRAGMA inside BEGIN..COMMIT must not ride the speculative
    sandbox onto the shared RW connection (connection-scoped settings
    would survive the rollback)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            (before,) = a.storage.conn.execute(
                "PRAGMA synchronous"
            ).fetchone()

            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (1, 'x')")
                c.query("PRAGMA synchronous = OFF")
                c.query("COMMIT")
                c.close()

            await asyncio.to_thread(drive)
            (after,) = a.storage.conn.execute(
                "PRAGMA synchronous"
            ).fetchone()
            assert after == before, (before, after)
        finally:
            await a.stop()

    run(main())


def test_pg_cte_dml_and_paren_select_in_txn(run):
    """CTE-led DML buffers like any write (never the sandbox, where a
    rollback would silently lose it); parenthesized compound SELECTs
    get read-your-writes like their bare form; RETURNING * sees
    wire-DDL column additions."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (1, 'a')")
                # CTE-led DML is a WRITE: buffered, applied at COMMIT
                _, _, tags, errs = c.query(
                    "WITH src AS (SELECT 2 AS id) "
                    "INSERT INTO tests SELECT id, 'b' FROM src"
                )
                assert not errs, errs
                # compound select sees both pending rows (sqlite
                # rejects PARENTHESIZED compound operands outright, so
                # only the bare form is executable either way)
                _, rows, _, errs = c.query(
                    "SELECT id FROM tests UNION ALL "
                    "SELECT 99 WHERE 1 = 0 ORDER BY id"
                )
                assert not errs and rows == [["1"], ["2"]], (rows, errs)
                c.query("COMMIT")
                _, rows, _, _ = c.query(
                    "SELECT id FROM tests ORDER BY id"
                )
                assert rows == [["1"], ["2"]]
                c.close()

            await asyncio.to_thread(drive)
            # both rows durably exist (the CTE insert was not lost)
            _, rows = a.storage.read_query(
                "SELECT id FROM tests ORDER BY id"
            )
            assert [r[0] for r in rows] == [1, 2]

            # declared_columns tracks wire DDL
            cols_before = a.storage.declared_columns("tests")
            assert cols_before == ("id", "text")
            a.execute_transaction([["ALTER TABLE tests ADD COLUMN note TEXT"]])
            assert a.storage.declared_columns("tests") == (
                "id", "text", "note"
            )
        finally:
            await a.stop()

    run(main())


def test_pg_savepoints(run):
    """SAVEPOINT / ROLLBACK TO / RELEASE against the buffered-write
    transaction model: ROLLBACK TO discards writes made after the mark
    and clears a failed state; RELEASE drops the mark; unknown names
    carry SQLSTATE 3B001."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (1, 'keep')")
                _, _, tags, errs = c.query("SAVEPOINT sp1")
                assert tags == ["SAVEPOINT"] and not errs
                c.query("INSERT INTO tests (id, text) VALUES (2, 'drop')")
                _, _, tags, errs = c.query("ROLLBACK TO SAVEPOINT sp1")
                assert tags == ["ROLLBACK"] and not errs
                # a failed statement aborts the txn; ROLLBACK TO heals it
                c.query("SAVEPOINT sp2")
                _, _, _, errs = c.query("SELECT nonsense_fn()")
                assert errs
                _, _, _, errs = c.query("SELECT 1")
                assert errs and c.last_error_codes == ["25P02"]
                _, _, tags, errs = c.query("ROLLBACK TO sp2")
                assert not errs
                cols, rows, _, errs = c.query("SELECT 1")
                assert not errs and rows == [["1"]]
                # unknown savepoint
                _, _, _, errs = c.query("ROLLBACK TO nope")
                assert errs and c.last_error_codes == ["3B001"]
                c.query("ROLLBACK")  # heal the 3B001-failed txn
                c.query("BEGIN")
                c.query("INSERT INTO tests (id, text) VALUES (3, 'keep2')")
                c.query("SAVEPOINT sp3")
                _, _, tags, errs = c.query("RELEASE SAVEPOINT sp3")
                assert tags == ["RELEASE"] and not errs
                c.query("COMMIT")
                c.close()

            await asyncio.to_thread(drive)
            _, rows = a.storage.read_query(
                "SELECT id, text FROM tests ORDER BY id")
            assert [tuple(r) for r in rows] == [(3, "keep2")]
        finally:
            await a.stop()

    run(main())


def test_pg_sqlstate_codes(run):
    """Errors carry the SQLSTATE a real server would send, not a
    catch-all syntax error (sql_state.rs parity)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                cases = [
                    ("SELECT * FROM no_such_tbl", "42P01"),
                    ("SELECT no_such_col FROM tests", "42703"),
                    ("SELECT no_such_fn(1)", "42883"),
                    ("SELECT FROM WHERE", "42601"),
                ]
                for sql, code in cases:
                    _, _, _, errs = c.query(sql)
                    assert errs and c.last_error_codes == [code], (
                        sql, c.last_error_codes)
                # constraint violations
                c.query("INSERT INTO tests (id, text) VALUES (1, 'x')")
                _, _, _, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (1, 'dup')")
                assert errs and c.last_error_codes == ["23505"]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_gucs_set_show_reset(run):
    """SET/SHOW/RESET are real session state: a SET value round-trips
    through SHOW, RESET restores the default, unknown parameters carry
    42704, SHOW ALL lists."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                _, rows, _, errs = c.query("SHOW server_version")
                assert not errs and rows == [["14.9"]]
                _, _, tags, _ = c.query("SET application_name = 'myapp'")
                assert tags == ["SET"]
                _, rows, _, _ = c.query("SHOW application_name")
                assert rows == [["myapp"]]
                c.query("SET search_path TO myschema, public")
                _, rows, _, _ = c.query("SHOW search_path")
                assert rows == [["myschema, public"]]
                c.query("RESET application_name")
                _, rows, _, _ = c.query("SHOW application_name")
                assert rows == [[""]]
                _, _, _, errs = c.query("SHOW no_such_guc")
                assert errs and c.last_error_codes == ["42704"]
                cols, rows, _, errs = c.query("SHOW ALL")
                assert not errs and cols == ["name", "setting", "description"]
                assert any(r[0] == "server_version" for r in rows)
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_cancel_request(run):
    """A CancelRequest bearing the BackendKeyData key interrupts the
    session's in-flight query, which fails with 57014; the session
    survives and keeps serving."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                import threading
                import time

                c = PgClient(*a.pg_addr)
                assert c.backend_key and c.backend_key != (0, 0)

                def fire_cancel():
                    time.sleep(0.4)
                    PgClient.cancel_request(*a.pg_addr, c.backend_key)

                t = threading.Thread(target=fire_cancel)
                t.start()
                # a deliberately slow query (recursive CTE spin)
                _, _, _, errs = c.query(
                    "WITH RECURSIVE spin(n) AS ("
                    " SELECT 1 UNION ALL SELECT n + 1 FROM spin"
                    " WHERE n < 300000000)"
                    " SELECT count(*) FROM spin"
                )
                t.join()
                assert errs, "query was not cancelled"
                assert c.last_error_codes == ["57014"], c.last_error_codes
                # session still usable
                _, rows, _, errs = c.query("SELECT 41 + 1")
                assert not errs and rows == [["42"]]
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_cancel_request_interrupts_write(run):
    """CancelRequest landing on an in-flight WRITE interrupts it (57014)
    instead of silently no-opping: the write connection is tracked while
    the storage lock is held (round-5 ADVICE item).  The aborted write
    must not have committed, and the session keeps serving.

    Sync is slowed way down: the sync loop's generate_sync takes the
    storage lock with a synchronous acquire ON the event loop, so with
    the test-speed cadence it would freeze the loop behind our
    minutes-long write and the cancel connection would never be
    served."""
    async def main():
        a = await launch_test_agent(
            pg_port=0, sync_interval_min=120, sync_interval_max=121,
        )
        try:
            def drive():
                import threading
                import time

                c = PgClient(*a.pg_addr)

                def fire_cancel():
                    time.sleep(0.4)
                    PgClient.cancel_request(*a.pg_addr, c.backend_key)

                t = threading.Thread(target=fire_cancel)
                t.start()
                # a deliberately slow WRITE: the aggregate forces the
                # whole recursive spin BEFORE any row is produced, so
                # the statement burns time in pure SQL (GIL released —
                # per-row CRR trigger UDF callbacks would starve the
                # event loop serving the cancel connection)
                _, _, _, errs = c.query(
                    "INSERT INTO tests (id, text)"
                    " SELECT n + 1000000, 'spin' FROM ("
                    "WITH RECURSIVE spin(n) AS ("
                    " SELECT 1 UNION ALL SELECT n + 1 FROM spin"
                    " WHERE n < 300000000) SELECT max(n) AS n FROM spin)"
                )
                t.join()
                assert errs, "write was not cancelled"
                assert c.last_error_codes == ["57014"], c.last_error_codes
                # the interrupted transaction rolled back: nothing stuck
                _, rows, _, errs = c.query(
                    "SELECT count(*) FROM tests WHERE text = 'spin'"
                )
                assert not errs and rows == [["0"]]
                # session still writable afterwards
                _, _, _, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (7001, 'after')"
                )
                assert not errs
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_cancel_request_interrupts_catalog_query(run):
    """CancelRequest landing on a catalog query interrupts it (57014):
    the shared catalog connection is tracked under the catalog lock
    (round-5 ADVICE item)."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                import threading
                import time

                c = PgClient(*a.pg_addr)

                def fire_cancel():
                    time.sleep(0.4)
                    PgClient.cancel_request(*a.pg_addr, c.backend_key)

                t = threading.Thread(target=fire_cancel)
                t.start()
                # a deliberately slow catalog read: the pg_class ref
                # routes the whole statement to the rendered catalog
                _, _, _, errs = c.query(
                    "WITH RECURSIVE spin(n) AS ("
                    " SELECT 1 UNION ALL SELECT n + 1 FROM spin"
                    " WHERE n < 300000000)"
                    " SELECT count(*) FROM spin, pg_class"
                )
                t.join()
                assert errs, "catalog query was not cancelled"
                assert c.last_error_codes == ["57014"], c.last_error_codes
                # catalog still serves afterwards
                _, rows, _, errs = c.query(
                    "SELECT count(*) FROM pg_catalog.pg_namespace"
                )
                assert not errs and int(rows[0][0]) >= 1
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())


def test_pg_orm_shaped_flows(run):
    """The verdict's named ORM shapes, end-to-end on the wire without
    regex probes: prepared INSERT..RETURNING with casts, upsert with
    excluded., CTE-led DML with a correct command tag, schema-qualified
    names, FOR UPDATE dropped."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                # prepared INSERT .. RETURNING with casts
                cols, rows, tag, err = c.prepared(
                    "INSERT INTO tests (id, text)"
                    " VALUES ($1::int8, $2::character varying(40))"
                    " RETURNING id, text",
                    (7, "cast me"),
                )
                assert err is None and tag == "INSERT 0 1"
                assert rows == [["7", "cast me"]]
                # upsert via excluded. (SQLAlchemy/ActiveRecord shape)
                cols, rows, tag, err = c.prepared(
                    "INSERT INTO tests (id, text) VALUES ($1, $2)"
                    " ON CONFLICT (id) DO UPDATE SET text = excluded.text"
                    " RETURNING id, text",
                    (7, "upserted"),
                )
                assert err is None and rows == [["7", "upserted"]]
                # CTE-led DML: proper INSERT tag (grammar, not regex)
                _, _, tags, errs = c.query(
                    "WITH v AS (SELECT 8 AS id)"
                    " INSERT INTO public.tests (id, text)"
                    " SELECT id, 'cte' FROM v")
                assert not errs and tags == ["INSERT 0 1"]
                # SELECT ... FOR UPDATE (row-lock clause dropped)
                _, rows, _, errs = c.query(
                    "SELECT id FROM tests WHERE id = 8 FOR UPDATE")
                assert not errs and rows == [["8"]]
                c.close()

            await asyncio.to_thread(drive)
            assert a.metrics.get_counter(
                "corro_pg_parse_fallbacks_total") in (0.0, None)
        finally:
            await a.stop()

    run(main())


def test_pg_statement_mix_metric_consistent_across_pipelines(run):
    """corro_pg_statements_total{kind=...} counts every pipeline: AST
    reads, token-pass FALLBACK reads (out-of-grammar statements must
    not vanish from the mix), catalog queries (kind=catalog from
    either pipeline), writes and utility statements."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def get(kind):
                return a.metrics.get_counter(
                    "corro_pg_statements_total", kind=kind) or 0.0

            def drive():
                c = PgClient(*a.pg_addr)
                before = {k: get(k) for k in
                          ("read", "write", "catalog", "utility")}
                # AST-pipeline read
                _, rows, _, errs = c.query("SELECT 1")
                assert not errs
                # token-pass FALLBACK read (PRAGMA is outside the
                # grammar but a legitimate sqlite read)
                _, rows, _, errs = c.query("PRAGMA user_version")
                assert not errs
                # catalog query (AST routing into _catalog_query)
                _, rows, _, errs = c.query(
                    "SELECT count(*) FROM pg_catalog.pg_class")
                assert not errs
                # write + utility
                _, _, tags, errs = c.query(
                    "INSERT INTO tests (id, text) VALUES (9, 'mix')")
                assert not errs and tags == ["INSERT 0 1"]
                _, _, tags, errs = c.query("SET application_name = 'x'")
                assert not errs and tags == ["SET"]
                c.close()
                assert get("read") >= before["read"] + 2, (
                    "fallback read not counted")
                assert get("catalog") >= before["catalog"] + 1
                assert get("write") >= before["write"] + 1
                assert get("utility") >= before["utility"] + 1

            await asyncio.to_thread(drive)
            # the statement-mix counter rode the fallback pipeline, not
            # a silent regression of the parser: the PRAGMA really fell
            # back
            assert a.metrics.get_counter(
                "corro_pg_parse_fallbacks_total") >= 1
        finally:
            await a.stop()

    run(main())


def test_pg_catalog_lock_created_at_server_startup(run):
    """The catalog lock must exist before any session thread runs (the
    old lazy check-then-set let two first-catalog-query sessions both
    install their own lock and race the shared connection)."""
    import threading

    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            lock = getattr(a, "_pg_catalog_lock", None)
            assert lock is not None, "serve_pg did not install the lock"
            # concurrent first-catalog-queries from two sessions: both
            # must serialize on the ONE startup lock and succeed
            errs = []

            def probe():
                try:
                    c = PgClient(*a.pg_addr)
                    _, rows, _, es = c.query(
                        "SELECT count(*) FROM pg_catalog.pg_class")
                    assert not es and rows
                    c.close()
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=probe) for _ in range(4)]
            await asyncio.to_thread(
                lambda: ([t.start() for t in ts],
                         [t.join() for t in ts]))
            assert not errs, errs
            assert getattr(a, "_pg_catalog_lock") is lock, (
                "a session replaced the startup lock")
        finally:
            await a.stop()

    run(main())


def test_pg_driver_setup_statements(run):
    """Driver/ORM session-setup shapes: SET TRANSACTION / SESSION
    CHARACTERISTICS / NAMES are accepted; SHOW TIME ZONE answers; a
    recursive CTE named like a catalog table stays a user query."""
    async def main():
        a = await launch_test_agent(pg_port=0)
        try:
            def drive():
                c = PgClient(*a.pg_addr)
                for sql in (
                    "SET TRANSACTION ISOLATION LEVEL READ COMMITTED",
                    "SET SESSION CHARACTERISTICS AS TRANSACTION"
                    " ISOLATION LEVEL SERIALIZABLE",
                    "SET NAMES 'UTF8'",
                ):
                    _, _, tags, errs = c.query(sql)
                    assert not errs and tags == ["SET"], (sql, errs)
                _, rows, _, errs = c.query("SHOW TIME ZONE")
                assert not errs and rows == [["UTC"]]
                # inside a txn too (SQLAlchemy fires it after BEGIN)
                c.query("BEGIN")
                _, _, tags, errs = c.query(
                    "SET TRANSACTION ISOLATION LEVEL REPEATABLE READ")
                assert not errs and tags == ["SET"]
                c.query("COMMIT")
                _, rows, _, errs = c.query(
                    "WITH RECURSIVE pg_class(n) AS ("
                    " SELECT 1 UNION ALL SELECT n + 1 FROM pg_class"
                    " WHERE n < 3) SELECT count(*) FROM pg_class")
                assert not errs and rows == [["3"]], (rows, errs)
                c.close()

            await asyncio.to_thread(drive)
        finally:
            await a.stop()

    run(main())
