"""Device-resident apply tests (docs/crdts.md "Device-resident apply").

The contract under test: with ``enable_device_cache()`` the batched
apply path seeds merges from the cross-batch clock cache and defers the
SQL flush behind the write-behind journal — and must leave EXACTLY the
state the per-change sequential oracle leaves, across cache hits,
misses, evictions, invalidations (local writes, compaction, snapshot
install), crash windows, and both array-store backends.  Plus a seeded
stale-cache corruption negative control proving the parity harness
actually reads through the cache.
"""

import random

import pytest

from corrosion_tpu.agent.metrics import Metrics
from corrosion_tpu.agent.pack import pack_values
from corrosion_tpu.agent.storage import CrConn
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.change import Change, SENTINEL_CID
from tests.test_apply_batched import (
    SITES,
    _assert_state_equal,
    _mk,
    _stream,
)


def _mk_dev(tmp_path, name, slots=None, backend="numpy"):
    """A CRR database on the device-resident apply path, columnar
    kernel forced for every batch size."""
    conn = _mk(tmp_path, name, columnar=True)
    conn.enable_device_cache(slots=slots, backend=backend)
    return conn


def _journal_rows(conn):
    return conn.conn.execute(
        "SELECT COUNT(*) FROM __corro_flush_journal"
    ).fetchone()[0]


# ---------------------------------------------------------------------------
# randomized parity: device-cached vs the sequential oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(8))
def test_device_parity_randomized(tmp_path, seed):
    """Interleaved applies, local writes (whole-cache invalidation via
    the local-write hook) and an out-of-band compaction invalidation:
    the device arm must match the ``_apply_one`` oracle after every
    round, and the flush journal must be empty after every barrier."""
    rng = random.Random(seed)
    seq = _mk(tmp_path, f"seq{seed}")
    dev = _mk_dev(tmp_path, f"dev{seed}", slots=64)
    for rnd in range(4):
        batch = _stream(rng, 60)
        with seq.apply_tx():
            n1 = seq.apply_changes_sequential_in_tx(list(batch))
        n2 = dev.apply_changes_batched(list(batch))
        assert n1 == n2, (seed, rnd, n1, n2)
        if rnd == 1:
            for c in (seq, dev):
                c.execute(
                    "INSERT OR REPLACE INTO items (id, a) "
                    "VALUES (4, 'mid')"
                )
        if rnd == 2:
            dev.device_cache_invalidate("compaction")
        dev.flush_barrier()
        assert _journal_rows(dev) == 0
        _assert_state_equal(seq, dev)
    assert dev.device_cache.invalidations.get("compaction", 0) > 0
    assert dev.device_cache.invalidations.get("local_write", 0) > 0
    seq.close()
    dev.close()


def _wide_changes(n_rows, col_version):
    """One cell change per pk over a WIDE pk range — capacity pressure
    for a small cache."""
    site = SITES[0]
    return [
        Change(
            table="items", pk=pack_values([i]), cid="a",
            val=f"v{col_version}-{i}", col_version=col_version,
            db_version=CrsqlDbVersion(col_version),
            seq=CrsqlSeq(i), site_id=site, cl=1,
        )
        for i in range(n_rows)
    ]


def test_eviction_pressure_parity(tmp_path):
    """More distinct pks than the cache has slots: capacity pressure
    clears the table (counted as evictions), the next batch re-seeds
    from SQLite, and state parity holds throughout."""
    seq = _mk(tmp_path, "evseq")
    dev = _mk_dev(tmp_path, "evdev", slots=64)  # max 64 rows / 64 cells
    for cv in (1, 2):
        changes = _wide_changes(150, cv)
        for lo in range(0, 150, 50):
            batch = changes[lo:lo + 50]
            with seq.apply_tx():
                seq.apply_changes_sequential_in_tx(list(batch))
            dev.apply_changes_batched(list(batch))
    dev.flush_barrier()
    _assert_state_equal(seq, dev)
    assert dev.device_cache.counters["evictions"] > 0
    seq.close()
    dev.close()


def test_stale_cache_corruption_detected(tmp_path):
    """Negative control: seed the cache with a CORRUPTED causal length
    and prove the oracle comparison diverges.  If this test ever
    passes equality, the apply path stopped reading through the cache
    and the whole parity suite above is vacuous."""
    seq = _mk(tmp_path, "corrseq")
    dev = _mk_dev(tmp_path, "corrdev")
    pk = pack_values([1])
    first = [Change(
        table="items", pk=pk, cid="a", val="v1", col_version=1,
        db_version=CrsqlDbVersion(1), seq=CrsqlSeq(0),
        site_id=SITES[0], cl=1,
    )]
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(first))
    dev.apply_changes_batched(list(first))
    dev.flush_barrier()
    _assert_state_equal(seq, dev)
    # corrupt the cached cl: pretend the row is at causal length 9
    tc = dev.device_cache._tables["items"]
    tc.row_cl[tc.pk_slot[pk]] = 9
    # a cl=2 delete must win against the real cl=1; against the
    # corrupted cl=9 the device arm wrongly keeps the row alive
    delete = [Change(
        table="items", pk=pk, cid=SENTINEL_CID, val=None,
        col_version=2, db_version=CrsqlDbVersion(2), seq=CrsqlSeq(0),
        site_id=SITES[1], cl=2,
    )]
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(delete))
    dev.apply_changes_batched(list(delete))
    dev.flush_barrier()
    with pytest.raises(AssertionError):
        _assert_state_equal(seq, dev)
    seq.close()
    dev.close()


# ---------------------------------------------------------------------------
# crash window: committed-but-unflushed winners
# ---------------------------------------------------------------------------


def test_crash_window_journal_recovery(tmp_path):
    """Kill the process between a committed device-merge and its async
    flush: the journal rows written inside the apply transaction must
    replay at reopen, losing NO committed winner (acceptance gate)."""
    rng = random.Random(99)
    seq = _mk(tmp_path, "cseq")
    dev = _mk_dev(tmp_path, "cdev", slots=128)
    for _ in range(5):
        batch = _stream(rng, 50)
        with seq.apply_tx():
            seq.apply_changes_sequential_in_tx(list(batch))
        dev.apply_changes_batched(list(batch))
    pend = len(dev._wb.pending)
    assert pend > 0, "nothing pending — crash window not exercised"
    assert _journal_rows(dev) > 0
    path = dev.path
    dev.conn.close()  # raw close: no drain — the simulated crash
    dev2 = CrConn(path, site_id=b"\x77" * 16)
    # boot classified the crash window: rows replayed, journal empty
    assert dev2.flush_journal_recovered == pend
    assert _journal_rows(dev2) == 0
    _assert_state_equal(seq, dev2)
    seq.close()
    dev2.close()


# ---------------------------------------------------------------------------
# write-behind barriers on the read paths
# ---------------------------------------------------------------------------


def test_read_paths_barrier_unflushed_winners(tmp_path):
    """``read_query`` and ``collect_changes_ro`` must never observe a
    merged-but-unflushed winner: both drain the write-behind queue
    before reading."""
    seq = _mk(tmp_path, "bseq")
    dev = _mk_dev(tmp_path, "bdev")
    batch = _wide_changes(10, 1)
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(batch))
    dev.apply_changes_batched(list(batch))
    assert len(dev._wb.pending) > 0  # winners not yet in SQLite
    _cols, rows = dev.read_query(
        "SELECT a FROM items WHERE id = 3"
    )
    assert rows == [("v1-3",)]
    assert len(dev._wb.pending) == 0  # the read drained the queue
    dev.apply_changes_batched(list(_wide_changes(10, 2)))
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(_wide_changes(10, 2)))
    with dev.reader() as conn:
        got = dev.collect_changes_ro(conn, (1, 64), SITES[0])
    want = seq.collect_changes((1, 64), SITES[0])
    assert got == want
    seq.close()
    dev.close()


# ---------------------------------------------------------------------------
# snapshot install: cache invalidated, journal purged (never replayed)
# ---------------------------------------------------------------------------


def test_snapshot_install_invalidates_and_purges_journal(tmp_path):
    """Installing a snapshot swaps the database file: every cached
    clock view is invalid, pending flushes target the dead inode, and
    any flush-journal rows the installed file carries are the DONOR's
    intents — purged without decoding (a receiver must never unpickle
    another node's journal payloads)."""
    donor = _mk(tmp_path, "donor")
    donor.execute("INSERT INTO items (id, a) VALUES (9, 'donor')")
    # a poisoned donor journal row: if install ever replays instead of
    # purging, the payload decode raises and this test fails loudly
    donor.conn.execute(
        "INSERT INTO __corro_flush_journal (tbl, payload) VALUES (?, ?)",
        ("items", b"\x01not-a-pickle"),
    )
    donor.conn.commit()
    donor_path = donor.path
    donor.close()

    dev = _mk_dev(tmp_path, "recv")
    dev.apply_changes_batched(_wide_changes(8, 1))
    assert len(dev._wb.pending) > 0
    dev.install_snapshot(donor_path)
    assert _journal_rows(dev) == 0
    assert len(dev._wb.pending) == 0
    assert dev.device_cache.invalidations.get("snapshot_install", 0) > 0
    _cols, rows = dev.read_query("SELECT a FROM items WHERE id = 9")
    assert rows == [("donor",)]
    # the cache re-seeds from the installed file: post-install applies
    # still match a fresh oracle replaying the same post-install stream
    oracle = _mk(tmp_path, "postseq")
    oracle.execute("INSERT INTO items (id, a) VALUES (9, 'donor')")
    post = _wide_changes(8, 3)
    with oracle.apply_tx():
        oracle.apply_changes_sequential_in_tx(list(post))
    dev.apply_changes_batched(list(post))
    dev.flush_barrier()
    got = dev.conn.execute(
        'SELECT pk, cid, col_version FROM "items__corro_clock" '
        'ORDER BY pk, cid'
    ).fetchall()
    want = oracle.conn.execute(
        'SELECT pk, cid, col_version FROM "items__corro_clock" '
        'ORDER BY pk, cid'
    ).fetchall()
    assert got == want
    oracle.close()
    dev.close()


# ---------------------------------------------------------------------------
# columnar fallback accounting (hostile batches under the device path)
# ---------------------------------------------------------------------------


def test_columnar_fallback_counter_and_dict_timing(tmp_path):
    """A batch the kernel cannot encode (col_version over the 62-bit
    key budget) must fall back to the dict oracle, count
    ``corro_apply_columnar_fallbacks_total{table=}``, time the merge
    under ``kernel=dict`` — and still match the sequential oracle
    (the device path materializes the dict seed view on fallback)."""
    seq = _mk(tmp_path, "fbseq")
    dev = _mk_dev(tmp_path, "fbdev")
    dev.metrics = Metrics()
    # prime the cache so the hostile batch seeds from HITS
    warm = _wide_changes(6, 1)
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(warm))
    dev.apply_changes_batched(list(warm))
    dev.flush_barrier()
    hostile = [Change(
        table="items", pk=pack_values([i]), cid="a", val="big",
        col_version=(1 << 62) + 5, db_version=CrsqlDbVersion(7),
        seq=CrsqlSeq(i), site_id=SITES[1], cl=1,
    ) for i in range(6)]
    with seq.apply_tx():
        seq.apply_changes_sequential_in_tx(list(hostile))
    dev.apply_changes_batched(list(hostile))
    dev.flush_barrier()
    _assert_state_equal(seq, dev)
    assert dev.metrics.get_counter(
        "corro_apply_columnar_fallbacks_total", table="items"
    ) >= 1
    n_dict, _total = dev.metrics.histogram_stats(
        "corro_apply_merge_seconds", kernel="dict"
    )
    assert n_dict >= 1
    seq.close()
    dev.close()


# ---------------------------------------------------------------------------
# backend bit-equality: NumPy store == JaxStore(x64) == uncached kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_store_backend_bit_equality(tmp_path, seed):
    """The JAX device store must be bit-identical to the NumPy twin,
    and both identical to the uncached columnar path — the tier-1
    ``JAX_PLATFORMS=cpu`` equality gate for the device arm."""
    from jax.experimental import enable_x64

    rng = random.Random(7000 + seed)
    streams = [_stream(rng, 50) for _ in range(3)]
    uncached = _mk(tmp_path, f"unc{seed}", columnar=True)
    dev_np = _mk_dev(tmp_path, f"np{seed}", backend="numpy")
    with enable_x64():
        dev_jx = _mk_dev(tmp_path, f"jx{seed}", backend="jax")
        for batch in streams:
            uncached.apply_changes_batched(list(batch))
            dev_np.apply_changes_batched(list(batch))
            dev_jx.apply_changes_batched(list(batch))
            dev_np.flush_barrier()
            dev_jx.flush_barrier()
            _assert_state_equal(uncached, dev_np)
            _assert_state_equal(uncached, dev_jx)
    for c in (uncached, dev_np, dev_jx):
        c.close()


# ---------------------------------------------------------------------------
# cache metric accounting
# ---------------------------------------------------------------------------


def test_cache_hit_miss_metrics_emitted(tmp_path):
    """Steady-state re-applies over the same rows are HITS; the deltas
    reach the metrics registry at commit, and the flush-pending gauge
    tracks the write-behind queue depth."""
    dev = _mk_dev(tmp_path, "metdev")
    dev.metrics = Metrics()
    dev.apply_changes_batched(_wide_changes(20, 1))  # cold: misses
    dev.apply_changes_batched(_wide_changes(20, 2))  # hot: hits
    dev.apply_changes_batched(_wide_changes(20, 3))
    m = dev.metrics
    assert m.get_counter_sum("corro_apply_cache_misses_total") >= 20
    assert m.get_counter_sum("corro_apply_cache_hits_total") >= 40
    assert m._gauges["corro_apply_flush_pending"][()] == 3.0
    dev.flush_barrier()
    assert m._gauges["corro_apply_flush_pending"][()] == 0.0
    dev.close()
