"""The agent's live gossip/sync wire IS the speedy byte format.

These tests act as a foreign peer speaking nothing but raw reference
bytes (speedy-encoded payloads in u32-BE LengthDelimited frames,
``broadcast.rs:37-137`` / ``sync.rs:18-87``) over a plain TCP socket —
no repo wire helpers on the "remote" side beyond the codec itself —
and assert the agent both understands and emits that exact format.
"""

import asyncio

import pytest

from corrosion_tpu.agent.pack import pack_values
from corrosion_tpu.agent.runtime import STREAM_BI, STREAM_UNI
from corrosion_tpu.agent.testing import launch_test_agent, wait_for
from corrosion_tpu.bridge import speedy
from corrosion_tpu.types import (
    ActorId,
    Changeset,
    ChangeV1,
    SyncNeedV1,
    SyncStateV1,
    Timestamp,
    Version,
)
from corrosion_tpu.types.actor import ClusterId
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.payload import BiPayload, BroadcastV1, UniPayload

FOREIGN = b"\xaa" * 16


def _foreign_change(version: int, pk_id: int, text: str) -> ChangeV1:
    changes = [
        Change(
            table="tests", pk=pack_values([pk_id]), cid=c, val=v,
            col_version=1, db_version=CrsqlDbVersion(version),
            seq=CrsqlSeq(i), site_id=FOREIGN, cl=1,
        )
        for i, (c, v) in enumerate([("id", pk_id), ("text", text)])
    ]
    return ChangeV1(
        actor_id=ActorId(FOREIGN),
        changeset=Changeset.full(
            Version(version), changes, (CrsqlSeq(0), CrsqlSeq(1)),
            CrsqlSeq(1), Timestamp(1000 + version),
        ),
    )


def test_agent_ingests_raw_speedy_uni_stream(tmp_path):
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        try:
            h, p = a.gossip_addr
            reader, writer = await asyncio.open_connection(h, p)
            writer.write(STREAM_UNI)
            payload = speedy.encode_uni_payload(
                UniPayload(
                    broadcast=BroadcastV1(change=_foreign_change(1, 7, "raw")),
                    cluster_id=ClusterId(0),
                )
            )
            writer.write(speedy.frame(payload))
            await writer.drain()
            await wait_for(
                lambda: a.storage.read_query(
                    "SELECT text FROM tests WHERE id = 7"
                )[1]
            )
            _, rows = a.storage.read_query(
                "SELECT text FROM tests WHERE id = 7"
            )
            assert rows == [("raw",)]
            writer.close()
        finally:
            await a.stop()

    asyncio.run(main())


def test_raw_speedy_sync_session_pulls_changes(tmp_path):
    """A foreign peer runs a whole sync session in reference bytes:
    SyncStart BiPayload -> State + Clock back -> Request -> Changesets."""
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        try:
            a.execute_transaction(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "synced"]]]
            )
            h, p = a.gossip_addr
            reader, writer = await asyncio.open_connection(h, p)
            writer.write(STREAM_BI)
            writer.write(
                speedy.frame(
                    speedy.encode_bi_payload(
                        BiPayload(actor_id=ActorId(FOREIGN)), ClusterId(0)
                    )
                )
            )
            writer.write(
                speedy.frame(speedy.encode_sync_message(Timestamp(123456)))
            )
            await writer.drain()

            frames = speedy.FrameReader()
            theirs = None
            got_clock = None
            changesets = []
            requested = False
            while True:
                data = await asyncio.wait_for(reader.read(65536), timeout=5)
                if not data:
                    break
                for payload in frames.feed(data):
                    msg = speedy.decode_sync_message(payload)
                    if isinstance(msg, SyncStateV1):
                        theirs = msg
                        head = theirs.heads[ActorId(a.actor_id)]
                        req = [
                            (
                                ActorId(a.actor_id),
                                [SyncNeedV1.full(1, int(head))],
                            )
                        ]
                        writer.write(
                            speedy.frame(
                                speedy.encode_sync_message(("request", req))
                            )
                        )
                        await writer.drain()
                        writer.write_eof()
                        requested = True
                    elif isinstance(msg, Timestamp):
                        got_clock = msg
                    elif isinstance(msg, ChangeV1):
                        changesets.append(msg)
            assert requested and theirs is not None
            assert got_clock is not None
            assert changesets, "server served no changesets"
            vals = {
                (c.cid, c.val)
                for cv in changesets
                for c in cv.changeset.changes
            }
            assert ("text", "synced") in vals
            writer.close()
        finally:
            await a.stop()

    asyncio.run(main())


def test_sync_rejects_cross_cluster_in_reference_bytes(tmp_path):
    async def main():
        a = await launch_test_agent(tmpdir=str(tmp_path))
        try:
            h, p = a.gossip_addr
            reader, writer = await asyncio.open_connection(h, p)
            writer.write(STREAM_BI)
            writer.write(
                speedy.frame(
                    speedy.encode_bi_payload(
                        BiPayload(actor_id=ActorId(FOREIGN)), ClusterId(9)
                    )
                )
            )
            await writer.drain()
            frames = speedy.FrameReader()
            msgs = []
            while True:
                data = await asyncio.wait_for(reader.read(65536), timeout=5)
                if not data:
                    break
                for payload in frames.feed(data):
                    msgs.append(speedy.decode_sync_message(payload))
            assert (
                "rejection",
                speedy.REJECTION_DIFFERENT_CLUSTER,
            ) in msgs
            writer.close()
        finally:
            await a.stop()

    asyncio.run(main())
