import numpy as np

from corrosion_tpu.sim import (
    ChurnConfig,
    EpidemicConfig,
    run_churn,
    run_epidemic,
    run_epidemic_seeds,
)


def test_small_epidemic_converges():
    cfg = EpidemicConfig(
        n_nodes=256, n_rows=4, ring0_size=32, max_ticks=96, chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=0)
    assert stats["converged_frac"] == 1.0
    assert stats["ticks_to_converge"] < 40
    assert stats["msgs_per_node_mean"] > 0


def test_seeds_distribution():
    cfg = EpidemicConfig(
        n_nodes=128, n_rows=4, ring0_size=16, max_ticks=96, chunk_ticks=8,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=8, seed=1)
    assert stats["converged_frac"] == 1.0
    assert stats["ticks_p99"] >= stats["ticks_p50"]


def test_partition_heal_with_loss():
    # BASELINE config #5 shape, tiny: 5% loss, 2-way partition healing at t=10
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=32,
        loss=0.05,
        partition_blocks=2,
        heal_tick=10,
        sync_interval=4,
        max_ticks=160,
        chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=2)
    assert stats["converged_frac"] == 1.0
    # convergence can't predate the heal
    assert stats["ticks_to_converge"] >= 10


def test_no_sync_partition_never_converges():
    # with sync disabled and tx budget drained before the heal, the writer's
    # side quiesces and the far side stays stale
    cfg = EpidemicConfig(
        n_nodes=128,
        n_rows=4,
        ring0_size=16,
        max_transmissions=3,
        partition_blocks=2,
        heal_tick=10_000,
        sync_interval=0,
        max_ticks=32,
        chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=3)
    assert stats["converged_frac"] == 0.0


def test_churn_detection_and_rejoin():
    cfg = ChurnConfig(n_nodes=64, kill_tick=4, revive_tick=40, max_ticks=160)
    stats = run_churn(cfg, seed=0)
    assert stats["detect_latency"] is not None and stats["detect_latency"] > 0
    assert stats["rejoin_latency"] is not None and stats["rejoin_latency"] >= 0
    assert stats["msgs_per_node_mean"] > 0


def test_track_hops_off_converges_with_null_hop_stats():
    """track_hops=False (the large-N knob) must run the whole pipeline
    without a hops array and report hop stats as None."""
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=16,
        max_transmissions=4,
        sync_interval=0,
        max_ticks=48,
        chunk_ticks=8,
        track_hops=False,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=4, seed=2)
    assert stats["converged_frac"] == 1.0
    assert stats["hops_p50"] is None and stats["hops_p99"] is None
    assert stats["msgs_per_node_mean"] > 0
