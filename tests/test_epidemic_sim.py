import numpy as np

from corrosion_tpu.sim import (
    ChurnConfig,
    EpidemicConfig,
    run_churn,
    run_epidemic,
    run_epidemic_seeds,
)


def test_small_epidemic_converges():
    cfg = EpidemicConfig(
        n_nodes=256, n_rows=4, ring0_size=32, max_ticks=96, chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=0)
    assert stats["converged_frac"] == 1.0
    assert stats["ticks_to_converge"] < 40
    assert stats["msgs_per_node_mean"] > 0


def test_seeds_distribution():
    cfg = EpidemicConfig(
        n_nodes=128, n_rows=4, ring0_size=16, max_ticks=96, chunk_ticks=8,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=8, seed=1)
    assert stats["converged_frac"] == 1.0
    assert stats["ticks_p99"] >= stats["ticks_p50"]


def test_partition_heal_with_loss():
    # BASELINE config #5 shape, tiny: 5% loss, 2-way partition healing at t=10
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=32,
        loss=0.05,
        partition_blocks=2,
        heal_tick=10,
        sync_interval=4,
        max_ticks=160,
        chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=2)
    assert stats["converged_frac"] == 1.0
    # convergence can't predate the heal
    assert stats["ticks_to_converge"] >= 10


def test_no_sync_partition_never_converges():
    # with sync disabled and tx budget drained before the heal, the writer's
    # side quiesces and the far side stays stale
    cfg = EpidemicConfig(
        n_nodes=128,
        n_rows=4,
        ring0_size=16,
        max_transmissions=3,
        partition_blocks=2,
        heal_tick=10_000,
        sync_interval=0,
        max_ticks=32,
        chunk_ticks=8,
    )
    stats = run_epidemic(cfg, seed=3)
    assert stats["converged_frac"] == 0.0


def test_churn_detection_and_rejoin():
    cfg = ChurnConfig(n_nodes=64, kill_tick=4, revive_tick=40, max_ticks=160)
    stats = run_churn(cfg, seed=0)
    assert stats["detect_latency"] is not None and stats["detect_latency"] > 0
    assert stats["rejoin_latency"] is not None and stats["rejoin_latency"] >= 0
    assert stats["msgs_per_node_mean"] > 0


def test_track_hops_off_converges_with_null_hop_stats():
    """track_hops=False (the large-N knob) must run the whole pipeline
    without a hops array and report hop stats as None."""
    cfg = EpidemicConfig(
        n_nodes=256,
        n_rows=4,
        ring0_size=16,
        max_transmissions=4,
        sync_interval=0,
        max_ticks=48,
        chunk_ticks=8,
        track_hops=False,
    )
    stats = run_epidemic_seeds(cfg, n_seeds=4, seed=2)
    assert stats["converged_frac"] == 1.0
    assert stats["hops_p50"] is None and stats["hops_p99"] is None
    assert stats["msgs_per_node_mean"] > 0


def test_oneway_partition_severs_exactly_the_listed_direction():
    """The directed-partition shape (EpidemicConfig.oneway_blocks): a
    writer in block 0 with 0→1 severed plateaus at the block fraction
    until the heal — while with only the REVERSE direction severed its
    wave crosses freely and converges before the heal.  The symmetric
    plan severs both ways, so the 0→1-only cell must match its
    pre-heal plateau and the 1→0-only cell must beat it."""
    from corrosion_tpu.sim.epidemic import run_epidemic_coverage

    base = dict(
        n_nodes=64, n_rows=4, fanout_ring0=0, fanout_global=3,
        ring0_size=1, max_transmissions=5, partition_blocks=2,
        heal_tick=24, backoff_ticks=2.5, sync_interval=8, sync_peers=1,
        max_ticks=256, chunk_ticks=8,
    )
    probe = 22  # just before the heal
    sev = run_epidemic_coverage(
        EpidemicConfig(**base, oneway_blocks=((0, 1),)), n_seeds=4,
    )
    sym = run_epidemic_coverage(
        EpidemicConfig(**base), n_seeds=4,
    )
    free = run_epidemic_coverage(
        EpidemicConfig(**base, oneway_blocks=((1, 0),)), n_seeds=4,
    )
    # severed direction: held at the block fraction, like symmetric
    assert abs(sev["coverage"][probe] - 0.5) < 0.1
    assert abs(sym["coverage"][probe] - 0.5) < 0.1
    # reachable direction: the wave crossed before the heal
    assert free["coverage"][probe] > 0.9
    # all three heal to full coverage
    for cov in (sev, sym, free):
        assert cov["converged_frac"] == 1.0


def test_het_ring_topology_slows_perm_kernel_tail():
    """The heterogeneous-RTT ring in the perm-fanout kernel: matched
    configs, convergence strictly later than uniform (the slow arc's
    scaled retransmit cadence drives the tail)."""
    base = dict(
        n_nodes=1024, n_rows=4, fanout_ring0=1, fanout_global=2,
        ring0_size=64, max_transmissions=8, loss=0.05, sync_interval=8,
        max_ticks=96, chunk_ticks=8, track_hops=False,
    )
    uni = run_epidemic_seeds(EpidemicConfig(**base), n_seeds=4, seed=0)
    het = run_epidemic_seeds(
        EpidemicConfig(**base, topology="het_ring", rtt_tiers=6),
        n_seeds=4, seed=0,
    )
    assert uni["converged_frac"] == het["converged_frac"] == 1.0
    assert het["ticks_p50"] > uni["ticks_p50"]


def test_wan_topology_gossip_isolation_and_sync_heal():
    """wan_two_region in the perm-fanout kernel: at full cross-region
    loss gossip alone never crosses; anti-entropy (QUIC streams with
    retries — models/sync.py keeps sessions lossless) heals across,
    so the same config with sync on converges."""
    import jax

    from corrosion_tpu.sim.epidemic import epidemic_init, epidemic_tick

    base = dict(
        n_nodes=512, n_rows=4, fanout_ring0=1, fanout_global=2,
        ring0_size=64, max_transmissions=8, loss=0.0,
        max_ticks=64, chunk_ticks=8, track_hops=False,
        topology="wan_two_region", wan_cross_loss=1.0,
    )
    iso = EpidemicConfig(**base, sync_interval=0)
    st = epidemic_init(iso)
    target = np.asarray(st.rows[0])
    key = jax.random.PRNGKey(3)
    for t in range(16):
        st = epidemic_tick(st, jax.random.fold_in(key, t), iso)
    holds = (np.asarray(st.rows) == target[None, :]).all(axis=1)
    assert holds[:256].sum() > 16
    assert holds[256:].sum() == 0
    healed = run_epidemic_seeds(
        EpidemicConfig(**base, sync_interval=4), n_seeds=2, seed=0,
    )
    assert healed["converged_frac"] == 1.0


def test_oneway_sync_needs_both_directions():
    """Anti-entropy sessions ride a bi-stream: ANY severed direction
    between the pair kills the session (the live open_bi semantics).
    With gossip disabled entirely (max_transmissions=0 after the
    writer's budget burns into its own block — here: fanout into a
    1-wide ring0 only), sync alone must NOT cross a one-way partition
    in either direction while it is active."""
    import jax
    import jax.numpy as jnp

    from corrosion_tpu.models.sync import SyncParams, sync_step

    n = 8
    pid = jnp.asarray([0, 0, 0, 0, 1, 1, 1, 1], jnp.int32)
    rows = jnp.zeros((n, 2), jnp.int32).at[0].set(5)
    params = SyncParams(
        n_nodes=n, peers_per_round=4, oneway_blocks=((0, 1),)
    )
    r = rows
    for t in range(6):
        r, _ = sync_step(
            r, jnp.zeros((n,), jnp.int32), jax.random.PRNGKey(t),
            params, partition_id=pid, partition_active=True,
        )
    # block 0 converged internally; block 1 saw nothing (a 1→0 pull
    # session would move data 0→1 over the severed return leg)
    assert bool(jnp.all(r[:4] == 5))
    assert bool(jnp.all(r[4:] == 0))
