"""Ingest backpressure + transport guards.

Parity targets: the reference's load-shed stress test
(corro-agent/src/agent/handlers.rs:1110-1194 — hold the write conn,
force queue drops, recover via sync), the bounded drop-oldest ingest
queue (handlers.rs:904-923), and foca's 1178 B SWIM packet cap
(broadcast/mod.rs:943).
"""

from __future__ import annotations

import asyncio

from corrosion_tpu.agent.runtime import Agent, AgentConfig
from corrosion_tpu.agent.testing import TEST_SCHEMA, launch_test_agent, wait_for
from corrosion_tpu.types import ActorId, ChangeSource, ChangeV1, Changeset
from corrosion_tpu.types.base import CrsqlSeq, Version


def _changeset(agent, version: int, db_version: int) -> ChangeV1:
    changes = agent.storage.collect_changes((db_version, db_version))
    last_seq = max(len(changes) - 1, 0)
    return ChangeV1(
        actor_id=ActorId(agent.actor_id),
        changeset=Changeset.full(
            Version(version), changes,
            (CrsqlSeq(0), CrsqlSeq(last_seq)), CrsqlSeq(last_seq),
            agent.clock.new_timestamp(),
        ),
    )


def test_ingest_queue_drop_oldest_and_sync_recovery(tmp_path):
    """Flood a node whose write path is blocked: the bounded queue drops
    oldest entries instead of growing; after unblocking, anti-entropy
    sync recovers every dropped version and the cluster converges."""
    async def main():
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        a = await launch_test_agent(tmpdir=str(tmp_path / "a"))
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
            tmpdir=str(tmp_path / "b"),
            processing_queue_len=40,
        )
        await wait_for(
            lambda: len(a.members.alive()) >= 1 and len(b.members.alive()) >= 1,
            timeout=10,
        )

        n = 120
        for i in range(n):
            a.execute_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"t{i}"))]
            )

        # block b's apply path (the reference holds the write conn) and
        # flood the ingest queue directly, simulating a broadcast storm
        b.storage._lock.acquire()
        try:
            for v in range(1, n + 1):
                b.enqueue_change(_changeset(a, v, v), ChangeSource.BROADCAST)
            assert len(b._ingest) <= b.config.processing_queue_len
            dropped = b.metrics.get_counter("corro_changes_dropped_total")
            assert dropped > 0, "expected drop-oldest under pressure"
        finally:
            b.storage._lock.release()

        def converged():
            _, rows = b.storage.read_query("SELECT COUNT(*) FROM tests")
            return rows[0][0] == n

        await wait_for(converged, timeout=30)
        # no gaps left: sync healed everything the queue dropped
        bv = b.bookie.for_actor(a.actor_id)
        assert bv.needed_spans() == []
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_large_changesets_ride_uni_streams(tmp_path):
    """A transaction far over any datagram MTU converges via the framed
    uni-stream path, chunked at the 8 KiB changeset budget."""
    async def main():
        a = await launch_test_agent()
        b = await launch_test_agent(
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"]
        )
        await wait_for(
            lambda: len(a.members.alive()) >= 1 and len(b.members.alive()) >= 1,
            timeout=10,
        )
        big = "x" * 2000
        stmts = [
            ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, big))
            for i in range(60)  # ~120 KiB of payload in ONE version
        ]
        a.execute_transaction(stmts)

        def converged():
            _, rows = b.storage.read_query("SELECT COUNT(*) FROM tests")
            return rows[0][0] == 60

        await wait_for(converged, timeout=30)
        # nothing oversized ever went out as a datagram
        assert a.metrics.get_counter("corro_udp_oversize_dropped_total") == 0
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_udp_oversize_guard(tmp_path):
    async def main():
        a = await launch_test_agent()
        a._send_udp(("127.0.0.1", 9), {"k": "junk", "pad": "y" * 4000})
        assert a.metrics.get_counter("corro_udp_oversize_dropped_total") == 1
        await a.stop()

    asyncio.run(main())


def test_applies_genuinely_overlap(tmp_path):
    """Up to max_concurrent_applies batches are in flight on the worker
    pool at once — two _apply_batch executions overlap in time (the
    reference runs <=5 concurrent process_multiple_changes,
    handlers.rs:742-956)."""
    import threading
    import time as _time

    async def main():
        (tmp_path / "n1").mkdir()
        (tmp_path / "n2").mkdir()
        a = await launch_test_agent(
            tmpdir=str(tmp_path / "n1"),
            apply_queue_len=1,       # every changeset = its own batch
            apply_queue_timeout=0.001,
        )
        b = await launch_test_agent(
            tmpdir=str(tmp_path / "n2"),
            bootstrap=[f"{a.gossip_addr[0]}:{a.gossip_addr[1]}"],
        )
        try:
            await wait_for(lambda: a.members.alive() and b.members.alive())
            # instrument: count concurrent _apply_batch entries on agent a
            orig = a._apply_batch
            state = {"cur": 0, "max": 0}
            guard = threading.Lock()

            def slow_apply(batch):
                with guard:
                    state["cur"] += 1
                    state["max"] = max(state["max"], state["cur"])
                _time.sleep(0.05)  # hold the slot so batches can overlap
                try:
                    return orig(batch)
                finally:
                    with guard:
                        state["cur"] -= 1

            a._apply_batch = slow_apply
            # a burst of separate transactions from b -> many changesets
            for i in range(12):
                b.execute_transaction([
                    ["INSERT INTO tests (id, text) VALUES (?, ?)",
                     [i, f"v{i}"]]
                ])
            await wait_for(
                lambda: a.storage.read_query(
                    "SELECT count(*) FROM tests")[1] == [(12,)],
                timeout=30,
            )
            assert state["max"] >= 2, (
                f"applies never overlapped (max in flight {state['max']})"
            )
        finally:
            await b.stop()
            await a.stop()

    asyncio.run(main())


def test_write_priority_ordering_under_held_lock():
    """SplitPool write-tier parity (agent.rs:614-765): with the writer
    held, queued waiters acquire in priority order — client write
    (HIGH, write_priority) before replication apply (NORMAL,
    write_normal) before maintenance (LOW, write_low) — regardless of
    arrival order."""
    import threading
    import time

    from corrosion_tpu.agent.locks import (
        PRIO_HIGH,
        PRIO_LOW,
        PRIO_NORMAL,
        PriorityLock,
    )

    lock = PriorityLock()
    order = []
    started = []

    def waiter(prio, name):
        started.append(name)
        with lock.prio(prio, name):
            order.append(name)

    with lock.prio(PRIO_NORMAL, "holder"):
        threads = []
        # arrival order deliberately inverted vs priority
        for prio, name in ((PRIO_LOW, "maintenance"),
                           (PRIO_NORMAL, "apply"),
                           (PRIO_HIGH, "client-write")):
            t = threading.Thread(target=waiter, args=(prio, name))
            t.start()
            threads.append(t)
            # let each enqueue before the next arrives
            deadline = time.monotonic() + 2.0
            while len(started) < len(threads):
                if time.monotonic() > deadline:
                    raise AssertionError("waiter failed to start")
                time.sleep(0.005)
        time.sleep(0.05)  # all three blocked on the held lock
    for t in threads:
        t.join(timeout=5)
    assert order == ["client-write", "apply", "maintenance"]


def test_storage_tiers_route_like_the_reference(tmp_path):
    """The actual storage paths carry the reference's tiers: write_tx
    (client) HIGH, apply_tx (replication) NORMAL, compaction LOW —
    under a held writer, a queued client write beats a queued apply."""
    import threading
    import time

    from corrosion_tpu.agent.locks import PRIO_LOW
    from corrosion_tpu.agent.storage import CrConn
    from corrosion_tpu.agent.schema import apply_schema

    st = CrConn(str(tmp_path / "t.db"))
    apply_schema(st, TEST_SCHEMA)
    order = []

    def client_write():
        with st.write_tx() as conn:
            conn.execute(
                "INSERT INTO tests (id, text) VALUES (1, 'hi')"
            )
        order.append("client")

    def replication_apply():
        with st.apply_tx():
            pass
        order.append("apply")

    def maintenance():
        with st._lock.prio(PRIO_LOW, "maintenance"):
            pass
        order.append("maintenance")

    with st._lock.prio(PRIO_LOW, "holder"):
        ts = []
        for fn in (maintenance, replication_apply, client_write):
            t = threading.Thread(target=fn)
            t.start()
            ts.append(t)
            time.sleep(0.05)  # enqueue in reverse-priority order
    for t in ts:
        t.join(timeout=5)
    assert order == ["client", "apply", "maintenance"]
    st.conn.close()


def test_apply_schema_migrates_indexes(tmp_path):
    """Secondary (non-unique) indexes in the schema file are applied
    like tables (schema.rs:276-530): created, redefined on change,
    dropped on removal — so group/join columns can actually be
    indexed for the matcher's scoped plans."""
    from corrosion_tpu.agent.schema import apply_schema
    from corrosion_tpu.agent.storage import CrConn

    base = """
    CREATE TABLE t (id INTEGER NOT NULL PRIMARY KEY,
                    a TEXT NOT NULL DEFAULT '',
                    b TEXT NOT NULL DEFAULT '');
    """
    st = CrConn(str(tmp_path / "i.db"))
    apply_schema(st, base + "CREATE INDEX t_a ON t (a);"
                            "CREATE INDEX t_b ON t (b);")

    def live():
        return dict(st.conn.execute(
            "SELECT name, sql FROM sqlite_master WHERE type='index' "
            "AND sql IS NOT NULL AND name LIKE 't\\_%' ESCAPE '\\' "
            "AND name NOT LIKE '%\\_\\_corro\\_%' ESCAPE '\\'"
        ).fetchall())

    n_crr_idx = len(st.conn.execute(
        "SELECT name FROM sqlite_master WHERE type='index' "
        "AND name LIKE '%\\_\\_corro\\_%' ESCAPE '\\'"
    ).fetchall())
    assert n_crr_idx > 0  # bookkeeping indexes exist...

    idx = live()
    assert set(idx) == {"t_a", "t_b"}
    # redefine one, drop the other
    apply_schema(st, base + "CREATE INDEX t_a ON t (a, b);")
    idx = live()
    assert set(idx) == {"t_a"}
    assert "a, b" in idx["t_a"]
    # ...and re-applying never drops them
    assert len(st.conn.execute(
        "SELECT name FROM sqlite_master WHERE type='index' "
        "AND name LIKE '%\\_\\_corro\\_%' ESCAPE '\\'"
    ).fetchall()) == n_crr_idx
    st.conn.close()
