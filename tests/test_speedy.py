"""speedy wire codec: round-trips + hand-derived golden byte vectors.

The golden vectors are computed by hand from the speedy 0.8 layout rules
(see corrosion_tpu/bridge/speedy.py docstring) so the byte format is
pinned independently of the encoder — a bug symmetric in encode/decode
cannot slip through.
"""

from __future__ import annotations

import struct

import pytest

from corrosion_tpu.bridge import speedy
from corrosion_tpu.types.actor import ActorId, ClusterId
from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
from corrosion_tpu.types.change import Change
from corrosion_tpu.types.changeset import Changeset, ChangeV1
from corrosion_tpu.types.hlc import Timestamp
from corrosion_tpu.types.payload import (
    BiPayload,
    BroadcastV1,
    SyncNeedV1,
    SyncStateV1,
    UniPayload,
)

A1 = ActorId(bytes(range(16)))
A2 = ActorId(bytes(range(16, 32)))
SITE = bytes(range(32, 48))


def mk_change(val=42, cid="x", seq=0):
    return Change(
        table="t",
        pk=b"\x01\x09\x01",
        cid=cid,
        val=val,
        col_version=1,
        db_version=CrsqlDbVersion(7),
        seq=CrsqlSeq(seq),
        site_id=SITE,
        cl=1,
    )


# ---------------------------------------------------------------------------
# golden byte vectors
# ---------------------------------------------------------------------------


def test_golden_change_bytes():
    data = speedy.Writer()
    speedy._w_change(data, mk_change())
    got = data.getvalue()
    expect = (
        struct.pack("<I", 1) + b"t"          # TableName: u32 len + utf8
        + struct.pack("<I", 3) + b"\x01\x09\x01"  # pk: Vec<u8>
        + struct.pack("<I", 1) + b"x"        # ColumnName
        + b"\x01" + struct.pack("<q", 42)    # SqliteValue::Integer tag+i64
        + struct.pack("<q", 1)               # col_version i64
        + struct.pack("<Q", 7)               # db_version u64
        + struct.pack("<Q", 0)               # seq u64
        + SITE                               # [u8; 16] raw
        + struct.pack("<q", 1)               # cl i64
    )
    assert got == expect


def test_golden_sqlite_value_variants():
    cases = [
        (None, b"\x00"),
        (5, b"\x01" + struct.pack("<q", 5)),
        (0.5, b"\x02" + struct.pack("<d", 0.5)),
        ("ab", b"\x03" + struct.pack("<I", 2) + b"ab"),
        (b"\xff", b"\x04" + struct.pack("<I", 1) + b"\xff"),
    ]
    for val, expect in cases:
        w = speedy.Writer()
        speedy._w_value(w, val)
        assert w.getvalue() == expect, val
        r = speedy.Reader(expect)
        assert speedy._r_value(r) == val


def test_golden_uni_payload_full_changeset():
    ts = Timestamp(123456789)
    cs = Changeset.full(
        Version(3), [mk_change()], (CrsqlSeq(0), CrsqlSeq(0)), CrsqlSeq(0), ts
    )
    payload = UniPayload(
        broadcast=BroadcastV1(change=ChangeV1(actor_id=A1, changeset=cs)),
        cluster_id=ClusterId(9),
    )
    got = speedy.encode_uni_payload(payload)

    w = speedy.Writer()
    speedy._w_change(w, mk_change())
    change_bytes = w.getvalue()
    expect = (
        struct.pack("<I", 0) * 3             # V1 / Broadcast / Change tags
        + A1.bytes                           # actor_id raw uuid
        + struct.pack("<I", 1)               # Changeset::Full tag
        + struct.pack("<Q", 3)               # version
        + struct.pack("<I", 1) + change_bytes  # Vec<Change>
        + struct.pack("<Q", 0) + struct.pack("<Q", 0)  # seqs range
        + struct.pack("<Q", 0)               # last_seq
        + struct.pack("<Q", 123456789)       # ts
        + struct.pack("<H", 9)               # cluster_id u16 (default_on_eof)
    )
    assert got == expect
    back = speedy.decode_uni_payload(got)
    assert back == payload


def test_golden_changeset_empty_with_optional_ts():
    cs = Changeset.empty((Version(2), Version(5)), ts=None)
    w = speedy.Writer()
    speedy._w_changeset(w, cs)
    assert w.getvalue() == (
        struct.pack("<I", 0)                 # Changeset::Empty tag
        + struct.pack("<Q", 2) + struct.pack("<Q", 5)
        + b"\x00"                            # Option::None
    )
    # default_on_eof: ts entirely absent also decodes
    r = speedy.Reader(struct.pack("<I", 0) + struct.pack("<Q", 2) + struct.pack("<Q", 5))
    back = speedy._r_changeset(r)
    assert back.versions == (Version(2), Version(5)) and back.ts is None


def test_golden_sync_state_bytes():
    st = SyncStateV1(
        actor_id=A1,
        heads={A2: Version(10)},
        need={A2: [(2, 4)]},
        partial_need={A2: {Version(5): [(0, 7)]}},
        last_cleared_ts=Timestamp(77),
    )
    got = speedy.encode_sync_message(st)
    expect = (
        struct.pack("<I", 0)                 # SyncMessage::V1
        + struct.pack("<I", 0)               # SyncMessageV1::State
        + A1.bytes
        + struct.pack("<I", 1) + A2.bytes + struct.pack("<Q", 10)   # heads
        + struct.pack("<I", 1) + A2.bytes                           # need map
        + struct.pack("<I", 1) + struct.pack("<Q", 2) + struct.pack("<Q", 4)
        + struct.pack("<I", 1) + A2.bytes                           # partial_need
        + struct.pack("<I", 1) + struct.pack("<Q", 5)
        + struct.pack("<I", 1) + struct.pack("<Q", 0) + struct.pack("<Q", 7)
        + b"\x01" + struct.pack("<Q", 77)    # Option<Timestamp>::Some
    )
    assert got == expect
    back = speedy.decode_sync_message(got)
    assert back == st


def test_sync_state_default_on_eof_ts():
    st = SyncStateV1(actor_id=A1, heads={}, need={}, partial_need={})
    full = speedy.encode_sync_message(st)
    # strip the trailing Option byte: still decodes, ts defaults to None
    back = speedy.decode_sync_message(full[:-1])
    assert back.last_cleared_ts is None and back.actor_id == A1


# ---------------------------------------------------------------------------
# round-trips
# ---------------------------------------------------------------------------


def test_roundtrip_changeset_variants():
    ts = Timestamp(999)
    variants = [
        Changeset.empty((Version(1), Version(3)), ts),
        Changeset.empty((Version(1), Version(3)), None),
        Changeset.empty_set([(Version(1), Version(2)), (Version(9), Version(9))], ts),
        Changeset.full(
            Version(4),
            [mk_change(v, c, s) for s, (v, c) in enumerate(
                [(None, "a"), (1.25, "b"), ("txt", "c"), (b"\x00\x01", "d")]
            )],
            (CrsqlSeq(0), CrsqlSeq(3)),
            CrsqlSeq(3),
            ts,
        ),
    ]
    for cs in variants:
        cv = ChangeV1(actor_id=A2, changeset=cs)
        data = speedy.encode_uni_payload(
            UniPayload(broadcast=BroadcastV1(change=cv))
        )
        back = speedy.decode_uni_payload(data)
        assert back.broadcast.change == cv


def test_roundtrip_bi_payload():
    for trace in (None, {"traceparent": "00-abc-def-01"},
                  {"traceparent": "00-a-b-01", "tracestate": "x=y"}):
        p = BiPayload(actor_id=A1, trace_ctx=trace)
        data = speedy.encode_bi_payload(p, ClusterId(3))
        back, cid = speedy.decode_bi_payload(data)
        assert back == p and cid == ClusterId(3)


def test_roundtrip_sync_messages():
    msgs = [
        Timestamp(123),
        ("rejection", speedy.REJECTION_MAX_CONCURRENCY),
        ("rejection", speedy.REJECTION_DIFFERENT_CLUSTER),
        ("request", [
            (A1, [SyncNeedV1.full(1, 5), SyncNeedV1.partial(3, [(0, 2), (5, 9)])]),
            (A2, [SyncNeedV1.empty(Timestamp(4)), SyncNeedV1.empty(None)]),
        ]),
        ChangeV1(
            actor_id=A1,
            changeset=Changeset.full(
                Version(1), [mk_change()], (CrsqlSeq(0), CrsqlSeq(0)),
                CrsqlSeq(0), Timestamp(1),
            ),
        ),
    ]
    for msg in msgs:
        back = speedy.decode_sync_message(speedy.encode_sync_message(msg))
        assert back == msg


def test_framing_roundtrip_and_partial():
    payloads = [b"aaa", b"", b"x" * 1000]
    buf = b"".join(speedy.frame(p) for p in payloads)
    frames, rest = speedy.deframe(buf)
    assert frames == payloads and rest == b""
    # split mid-frame
    frames1, rest1 = speedy.deframe(buf[:5])
    assert frames1 == [] or frames1 == [b"aaa"]
    frames2, rest2 = speedy.deframe(rest1 + buf[5:])
    assert frames1 + frames2 == payloads and rest2 == b""


def test_frame_length_guard():
    bad = struct.pack(">I", speedy.MAX_FRAME_LEN + 1) + b"x"
    with pytest.raises(speedy.SpeedyError):
        speedy.deframe(bad)


def test_decode_rejects_trailing_garbage():
    data = speedy.encode_sync_message(Timestamp(5)) + b"\x00"
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_sync_message(data)


# ---------------------------------------------------------------------------
# traced uni envelope (broadcast-path trace propagation)
# ---------------------------------------------------------------------------


def _classic_uni_bytes():
    cs = Changeset.full(
        Version(1), [mk_change()], (CrsqlSeq(0), CrsqlSeq(0)),
        CrsqlSeq(0), Timestamp(1),
    )
    return speedy.encode_uni_payload(
        UniPayload(
            broadcast=BroadcastV1(
                change=ChangeV1(actor_id=A1, changeset=cs)
            )
        )
    )


TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


def test_traced_uni_roundtrip():
    classic = _classic_uni_bytes()
    wrapped = speedy.encode_traced_uni(classic, TP, hop=3)
    payload, tp, hop = speedy.decode_traced_uni(wrapped)
    assert payload == classic and tp == TP and hop == 3
    # no traceparent variant
    payload, tp, hop = speedy.decode_traced_uni(
        speedy.encode_traced_uni(classic, None, hop=0)
    )
    assert payload == classic and tp is None and hop == 0


def test_traced_uni_golden_bytes():
    """Pin the envelope layout independently of the codec: u8 version,
    u8 hop, speedy Option<String> traceparent, then the classic bytes."""
    classic = _classic_uni_bytes()
    wrapped = speedy.encode_traced_uni(classic, TP, hop=2)
    expect = (
        b"\x01"                       # envelope version
        + b"\x02"                     # hop
        + b"\x01"                     # Option tag: Some
        + struct.pack("<I", len(TP)) + TP.encode()
        + classic
    )
    assert wrapped == expect
    none_wrapped = speedy.encode_traced_uni(classic, None, hop=0)
    assert none_wrapped == b"\x01\x00\x00" + classic


def test_traced_uni_old_format_decodes_unchanged():
    """Backward compat (the migration contract): classic UniPayload
    bytes — first byte 0x00, the u32-LE V1 tag — pass through both the
    decoder and the offset walker untouched."""
    classic = _classic_uni_bytes()
    assert classic[0] == 0
    payload, tp, hop = speedy.decode_traced_uni(classic)
    assert payload == classic and tp is None and hop == 0
    assert speedy.traced_uni_payload_start(classic) == 0
    # and the decoded change is byte-for-byte the classic decode
    up = speedy.decode_uni_payload(payload)
    assert up.broadcast.change.actor_id == A1


def test_traced_uni_payload_start_matches_decoder():
    classic = _classic_uni_bytes()
    for tp, hop in ((TP, 1), (None, 0)):
        wrapped = speedy.encode_traced_uni(classic, tp, hop)
        start = speedy.traced_uni_payload_start(wrapped)
        assert wrapped[start:] == classic


def test_traced_uni_hostile_inputs():
    classic = _classic_uni_bytes()
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_traced_uni(b"")
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_traced_uni(b"\x07" + classic)  # unknown version
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(b"\x07" + classic)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(b"\x01\x00")  # truncated option
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(b"\x01\x00\x02")  # bad Option tag
    # oversized traceparent: rejected by BOTH the walker and the decoder
    big = b"\x01\x00\x01" + struct.pack("<I", 4096) + b"x" * 4096 + classic
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(big)
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_traced_uni(big)
    # the bound is in BYTES on both sides: a traceparent of 33
    # two-byte UTF-8 chars (66 bytes > MAX, 33 chars < MAX) must be
    # rejected by BOTH — a char-count bound in the decoder would let
    # it pass while the walker (live ingest's prelude screen) drops
    # the frame, so live and det would diverge on identical bytes
    multi = "é" * 33
    assert len(multi) <= speedy.MAX_TRACEPARENT_LEN
    assert len(multi.encode("utf-8")) > speedy.MAX_TRACEPARENT_LEN
    sneaky = speedy.encode_traced_uni(classic, multi)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(sneaky)
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_traced_uni(sneaky)
    # invalid UTF-8 traceparent bytes: the walker passes them (it never
    # decodes), so the decoder MUST raise SpeedyError — a raw
    # UnicodeDecodeError would escape callers' `except SpeedyError`
    # count-and-drop handling and crash the frame's consumer
    bad_utf8 = (b"\x01\x00\x01" + struct.pack("<I", 2) + b"\xff\xfe"
                + classic)
    assert speedy.traced_uni_payload_start(bad_utf8) == 9
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_traced_uni(bad_utf8)


# ---------------------------------------------------------------------------
# signed uni envelope (signed changeset attribution, docs/faults.md)
# ---------------------------------------------------------------------------

SIG = bytes(range(64))


def test_signed_uni_roundtrip():
    classic = _classic_uni_bytes()
    for tp, hop, sig in (
        (TP, 3, SIG), (None, 0, SIG), (TP, 1, None), (None, 0, None),
    ):
        wrapped = speedy.encode_signed_uni(classic, tp, hop, sig)
        payload, got_tp, got_hop, got_sig = speedy.decode_uni_envelope(
            wrapped
        )
        assert payload == classic
        assert (got_tp, got_hop, got_sig) == (tp, hop, sig)
        # the walker lands exactly where the decoder says the classic
        # bytes start, on every field combination
        start = speedy.traced_uni_payload_start(wrapped)
        assert wrapped[start:] == classic


def test_signed_uni_golden_bytes():
    """The v2 layout, byte for byte: u8 2 | u8 hop | Option<tp> |
    Option<[u8;64] sig raw, no length prefix> | classic payload."""
    classic = _classic_uni_bytes()
    wrapped = speedy.encode_signed_uni(classic, None, 2, SIG)
    assert wrapped == b"\x02\x02\x00\x01" + SIG + classic
    no_sig = speedy.encode_signed_uni(classic, None, 2, None)
    assert no_sig == b"\x02\x02\x00\x00" + classic
    with_tp = speedy.encode_signed_uni(classic, TP, 0, SIG)
    tp_bytes = TP.encode()
    assert with_tp == (
        b"\x02\x00\x01" + struct.pack("<I", len(tp_bytes)) + tp_bytes
        + b"\x01" + SIG + classic
    )


def test_signed_uni_envelope_versions_interoperate():
    """decode_uni_envelope accepts all three wire formats; the legacy
    decode_traced_uni surface keeps working on v2 frames (dropping the
    signature), so pre-signing consumers never break."""
    classic = _classic_uni_bytes()
    v1 = speedy.encode_traced_uni(classic, TP, 1)
    v2 = speedy.encode_signed_uni(classic, TP, 1, SIG)
    assert speedy.decode_uni_envelope(classic) == (classic, None, 0, None)
    assert speedy.decode_uni_envelope(v1) == (classic, TP, 1, None)
    assert speedy.decode_uni_envelope(v2) == (classic, TP, 1, SIG)
    assert speedy.decode_traced_uni(v2) == (classic, TP, 1)


def test_signed_uni_hostile_inputs():
    classic = _classic_uni_bytes()
    wrapped = speedy.encode_signed_uni(classic, TP, 1, SIG)
    # wrong sig length at ENCODE time
    with pytest.raises(speedy.SpeedyError):
        speedy.encode_signed_uni(classic, None, 0, b"short")
    with pytest.raises(speedy.SpeedyError):
        speedy.encode_signed_uni(classic, None, 0, SIG + b"x")
    # flipped version byte: unknown envelope on BOTH sides
    flipped = b"\x07" + wrapped[1:]
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_uni_envelope(flipped)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(flipped)
    # truncated signature: structural, rejected by BOTH sides
    trunc = speedy.encode_signed_uni(classic, None, 0, SIG)[: 4 + 40]
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_uni_envelope(trunc)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(trunc)
    # bad sig Option tag
    bad_tag = b"\x02\x00\x00\x07" + classic
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_uni_envelope(bad_tag)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(bad_tag)
    # truncated right after the header
    for cut in (b"\x02", b"\x02\x00", b"\x02\x00\x00"):
        with pytest.raises(speedy.SpeedyError):
            speedy.traced_uni_payload_start(cut)
    # oversized traceparent still rejected under v2
    big = (b"\x02\x00\x01" + struct.pack("<I", 4096) + b"x" * 4096
           + b"\x00" + classic)
    with pytest.raises(speedy.SpeedyError):
        speedy.traced_uni_payload_start(big)
    with pytest.raises(speedy.SpeedyError):
        speedy.decode_uni_envelope(big)


def test_signed_uni_walker_decoder_parity_fuzz():
    """Mutation corpus over all three envelope versions: whenever the
    offset walker (live ingest's prelude screen) REJECTS a frame, the
    full decoder must reject it too — and whenever both accept, they
    must agree on where the classic payload starts.  (The walker may
    be more permissive only about CONTENT it never inspects, e.g.
    traceparent UTF-8 — the PR 6 precedent.)"""
    import random

    classic = _classic_uni_bytes()
    corpus = [
        classic,
        speedy.encode_traced_uni(classic, TP, 1),
        speedy.encode_traced_uni(classic, None, 0),
        speedy.encode_signed_uni(classic, TP, 1, SIG),
        speedy.encode_signed_uni(classic, None, 2, SIG),
        speedy.encode_signed_uni(classic, TP, 0, None),
    ]
    rng = random.Random(0xC0FFEE)
    cases = list(corpus)
    for base in corpus:
        for _ in range(80):
            mutated = bytearray(base)
            op = rng.randrange(3)
            if op == 0 and mutated:  # flip a byte
                i = rng.randrange(len(mutated))
                mutated[i] ^= 1 << rng.randrange(8)
            elif op == 1:            # truncate
                mutated = mutated[: rng.randrange(len(mutated) + 1)]
            else:                    # append junk
                mutated += bytes(
                    rng.randrange(256) for _ in range(rng.randrange(8))
                )
            cases.append(bytes(mutated))
    for data in cases:
        try:
            start = speedy.traced_uni_payload_start(data)
            walker_ok = True
        except speedy.SpeedyError:
            walker_ok = False
        try:
            payload, _tp, _hop, _sig = speedy.decode_uni_envelope(data)
            decoder_ok = True
        except speedy.SpeedyError:
            decoder_ok = False
        if not walker_ok:
            assert not decoder_ok, (
                f"walker rejected but decoder accepted: {data!r}"
            )
        if walker_ok and decoder_ok:
            assert data[start:] == payload, (
                f"walker/decoder disagree on payload start: {data!r}"
            )
