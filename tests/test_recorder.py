"""Flight recorder bounds + timeline assembly.

The recorder's contract is that it can run always-on: the ring must
stay inside its configured budget under sustained snapshot + event
load, the jsonl export must rotate exactly once and count every drop
after that, and the crash-dump hook must flush the ring when an agent
task dies on an unhandled exception.  The timeline half: per-node
rings merge on the HLC axis, and the trajectory gates compare a
coverage curve against a predicted one with named tolerances.
"""

from __future__ import annotations

import asyncio
import json
import os

import pytest

from corrosion_tpu.agent.metrics import Metrics
from corrosion_tpu.agent.recorder import EVENT_KINDS, FlightRecorder
from corrosion_tpu.types import HLClock


def _recorder(tmp_path=None, **kw):
    return FlightRecorder(Metrics(), HLClock(), **kw)


# -- ring bounds -------------------------------------------------------


def test_ring_stays_within_budget_under_sustained_load():
    """Sustained snapshot + event load must never grow the ring past
    ring_max — the recorder is always-on and its memory is the ring."""
    rec = _recorder(ring_max=64)
    rec.metrics.counter("corro_test_total")
    for i in range(500):
        rec.event("write_group_fallback", reason="stmt")
        if i % 3 == 0:
            rec.metrics.counter("corro_test_total")
            rec.snapshot_once()
    assert len(rec.entries()) == 64
    assert rec.events == 500
    assert rec.snapshots == 167
    # newest records won: the ring's tail is the latest history
    assert rec.entries()[-1]["t"] in ("event", "snap")
    hlcs = [e["hlc"] for e in rec.entries()]
    assert hlcs == sorted(hlcs)  # per-node records strictly ordered


def test_unregistered_event_kind_raises():
    rec = _recorder()
    with pytest.raises(ValueError):
        rec.event("not_a_registered_kind")


def test_snapshot_carries_counter_deltas_not_totals():
    rec = _recorder()
    rec.metrics.counter("corro_test_total", 5.0)
    first = rec.snapshot_once()
    assert first["counters_delta"]["corro_test_total"] == 5.0
    rec.metrics.counter("corro_test_total", 2.0)
    second = rec.snapshot_once()
    assert second["counters_delta"]["corro_test_total"] == 2.0
    third = rec.snapshot_once()
    # unchanged series are omitted entirely — a snapshot is a diff
    assert "corro_test_total" not in third["counters_delta"]


# -- jsonl export: one rotation, drops counted -------------------------


def test_export_rotates_exactly_once_then_counts_drops(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    rec = _recorder(export_path=path, export_max_bytes=2048,
                    ring_max=32)
    for _ in range(200):
        rec.event("write_group_fallback", reason="stmt")
    # events only ENQUEUE export lines (disk I/O must stay off the
    # seams that emit them); the snapshot worker / close / crash dump
    # flush — here, explicitly
    rec.flush_export()
    assert os.path.exists(path + ".1")  # exactly one rotation target
    assert os.path.getsize(path + ".1") <= 2048 + 256
    assert os.path.getsize(path) <= 2048
    assert rec.export_dropped > 0
    assert rec.metrics.get_counter(
        "corro_flight_export_dropped_total"
    ) == float(rec.export_dropped)
    # the exported lines are valid json records
    with open(path + ".1") as f:
        for line in f:
            assert json.loads(line)["t"] == "event"
    # total on-disk footprint stays <= 2 x max_bytes: later events keep
    # dropping (at flush) instead of rotating again
    before = rec.export_dropped
    rec.event("write_group_fallback", reason="abort")
    rec.flush_export()
    assert rec.export_dropped == before + 1


# -- crash dump --------------------------------------------------------


def test_crash_dump_flushes_on_unhandled_task_exception(tmp_path):
    """An agent task dying on an unhandled exception must flush the
    flight ring to the crash path — the supervisor wiring, tested
    through a real (offline) agent."""
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        assert a.flight is not None
        a.flight.event("write_group_fallback", reason="stmt")

        async def boom():
            raise RuntimeError("injected")

        async def drive():
            t = a._spawn_task(boom(), "boom")
            with pytest.raises(RuntimeError):
                await t

        asyncio.run(drive())
        crash = os.path.join(str(tmp_path), "flight_crash.jsonl")
        assert os.path.exists(crash)
        recs = [json.loads(l) for l in open(crash)]
        kinds = [r.get("kind") for r in recs if r["t"] == "event"]
        assert "write_group_fallback" in kinds
        assert "crash_dump" in kinds  # the flush marker itself
        dump = next(r for r in recs if r.get("kind") == "crash_dump")
        assert "injected" in dump["attrs"]["reason"]
    finally:
        a.storage.close()


def test_cancellation_does_not_crash_dump(tmp_path):
    from corrosion_tpu.agent.testing import make_offline_agent

    a = make_offline_agent(tmpdir=str(tmp_path))
    try:
        async def forever():
            await asyncio.sleep(3600)

        async def drive():
            t = a._spawn_task(forever(), "forever")
            await asyncio.sleep(0)
            t.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t

        asyncio.run(drive())
        assert not os.path.exists(
            os.path.join(str(tmp_path), "flight_crash.jsonl")
        )
    finally:
        a.storage.close()


# -- timeline assembly + trajectory gates ------------------------------


def test_flight_timeline_merges_on_hlc_axis(tmp_path):
    """Two nodes with skewed WALL stamps still merge in HLC order."""
    from corrosion_tpu.devcluster import ClusterObserver

    class FakeAgent:
        def __init__(self, rec):
            self.flight = rec

    from corrosion_tpu.types import Timestamp

    a, b = _recorder(ring_max=16), _recorder(ring_max=16)
    a.event("breaker_open", addr="x:1")
    # b's clock merged a's EVENT stamp (observation stamps don't
    # advance a's own clock): b's next observation is strictly after,
    # even inside one 65 µs HLC grain, regardless of wall order
    b.clock.update_with_timestamp(Timestamp(a.entries()[-1]["hlc"]))
    b.event("breaker_close", addr="x:1")
    obs = ClusterObserver({"a": FakeAgent(a), "b": FakeAgent(b)})
    # scramble wall stamps: HLC must still win the merge order
    ents_a = a.entries()
    ents_a[0]["wall"] += 1e6
    tl = obs.flight_events()
    assert [e["kind"] for e in tl] == ["breaker_open", "breaker_close"]
    assert [e["node"] for e in tl] == ["a", "b"]


def test_trajectory_gates_named_tolerances():
    from corrosion_tpu.sim.timeline import (
        FULL_COV,
        PLATEAU_TOL,
        trajectory_gates,
    )

    pred = {
        "times_s": [0.02 * (i + 1) for i in range(64)],
        # plateau at 0.5 until tick 32 (0.64 s), then full
        "coverage": [min(0.5, 0.1 * (i + 1)) if i < 32 else 1.0
                     for i in range(64)],
        "t_at_coverage": {str(FULL_COV): 0.66, "1.0": 0.66},
    }
    live_ok = {
        "converged": True,
        "coverage": {
            "expected": 10,
            # half the pairs in fast, the rest well after the heal
            "offsets_s": [0.0] * 5 + [1.1] * 5,
            "t_at_coverage": {str(FULL_COV): 1.1, "1.0": 1.1},
        },
    }
    out = trajectory_gates(live_ok, pred, heal_after=0.64)
    assert out["gates"]["plateau_matches"]
    assert out["gates"]["partition_held"]
    assert out["gates"]["recovery_within_budget"]
    assert out["plateau_tolerance"] == PLATEAU_TOL
    assert out["recovery_budget_s"] is not None

    # a run that never plateaued (partition did not hold) fails the
    # plateau gate; one that recovers past the budget fails recovery
    live_no_plateau = {
        "converged": True,
        "coverage": {
            "expected": 10,
            "offsets_s": [0.0] * 10,
            "t_at_coverage": {str(FULL_COV): 0.0, "1.0": 0.0},
        },
    }
    out2 = trajectory_gates(live_no_plateau, pred, heal_after=0.64)
    assert not out2["gates"]["plateau_matches"]
    assert not out2["gates"]["partition_held"]
    live_slow = {
        "converged": True,
        "coverage": {
            "expected": 10,
            "offsets_s": [0.0] * 5 + [99.0] * 5,
            "t_at_coverage": {str(FULL_COV): 99.0, "1.0": 99.0},
        },
    }
    out3 = trajectory_gates(live_slow, pred, heal_after=0.64)
    assert not out3["gates"]["recovery_within_budget"]


def test_kernel_coverage_curve_shape():
    """The per-tick prediction shows the partition signature: a
    plateau at the severed-block fraction, then full coverage only
    after the heal tick."""
    from corrosion_tpu.sim.timeline import (
        TICK_S,
        curve_value_at,
        kernel_coverage_prediction,
    )

    pred = kernel_coverage_prediction(16, heal_tick=16, seeds=4)
    assert pred["coverage"][-1] == 1.0
    plateau = curve_value_at(
        pred["times_s"], pred["coverage"], 16 * TICK_S - 0.001
    )
    assert 0.2 <= plateau <= 0.75  # severed-block fraction, not full
    full_t = pred["t_at_coverage"]["1.0"]
    assert full_t is not None and full_t > 16 * TICK_S - 1e-9


def test_small_timeline_cell_end_to_end(tmp_path):
    """A small live partition-heal cell produces a timeline (snapshots
    + events) and a coverage curve with the plateau signature."""
    from corrosion_tpu.sim.timeline import agent_timeline_cell

    live = asyncio.run(agent_timeline_cell(
        n=5, writes=4, heal_after=0.5, timeout=60.0,
        base_dir=str(tmp_path),
    ))
    assert live["converged"]
    cov = live["coverage"]
    assert cov["waves"] == 4
    assert cov["expected"] == 20
    # every wave reached every node and provenance saw it
    assert cov["samples"] + cov["missing"] == cov["expected"]
    assert cov["t_at_coverage"]["1.0"] is not None
    tl = live["timeline"]
    assert tl["snapshots"] > 0
    assert tl["event_counts"].get("sync_client_start", 0) > 0


def test_crash_schedule_markers_reach_merged_timeline(tmp_path):
    """run_crash_schedule journals `crash` into the dying ring (kept as
    a controller orphan) and `restart` into the respawn; the observer
    built with faults=ctrl must surface BOTH in the merged timeline —
    a death must not erase the history that led up to it."""
    from corrosion_tpu.devcluster import (
        ClusterObserver,
        Topology,
        run_crash_schedule,
        run_inprocess,
    )
    from corrosion_tpu.faults import CrashEvent, FaultController, FaultPlan

    async def main():
        plan = FaultPlan(
            seed=3,
            crashes=(CrashEvent("n1", at=0.05, restart_at=0.3),),
        )
        ctrl = FaultController(plan)
        agents = await run_inprocess(
            Topology.parse("n0 -> n1"), faults=ctrl,
            base_dir=str(tmp_path), subs_enabled=False, api_port=None,
            flight_interval_s=0.25,
        )
        try:
            ctrl.restart_clock()
            await run_crash_schedule(ctrl)
            obs = ClusterObserver(ctrl.agents, faults=ctrl)
            kinds = [
                (e["node"], e["kind"]) for e in obs.flight_events()
            ]
            assert ("n1", "crash") in kinds
            assert ("n1", "restart") in kinds
            # the orphaned ring came from the controller, not the
            # (respawned) live agent
            assert ctrl.flight_orphans and ctrl.flight_orphans[0][0] == "n1"
        finally:
            for a in list(ctrl.agents.values()):
                try:
                    await a.stop()
                except Exception:
                    pass

    asyncio.run(main())


def test_event_kinds_registry_is_closed():
    """Every registered kind has a non-empty description (the doc lint
    in test_telemetry.py pins the registry against docs/telemetry.md)."""
    assert all(isinstance(v, str) and v for v in EVENT_KINDS.values())
