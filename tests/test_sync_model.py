import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.sync import SyncParams, bitmap_needs, sync_step
from corrosion_tpu.ops.keys import DEFAULT_CODEC as C
from corrosion_tpu.types import ActorId, SyncStateV1, Version


def test_sync_heals_isolated_node():
    n = 8
    p = SyncParams(n_nodes=n, peers_per_round=2)
    base = C.pack(jnp.ones((n, 4), jnp.int32), jnp.ones((n, 4), jnp.int32),
                  jnp.zeros((n, 4), jnp.int32))
    news = C.pack(jnp.ones((4,), jnp.int32), jnp.full((4,), 2, jnp.int32),
                  jnp.ones((4,), jnp.int32))
    # everyone but node 3 already has the news
    rows = jnp.tile(news, (n, 1)).at[3].set(base[3])
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(0)
    for t in range(6):
        rows, msgs = sync_step(rows, msgs, jax.random.fold_in(key, t), p)
        if bool(jnp.all(rows == news[None, :])):
            break
    assert bool(jnp.all(rows == news[None, :]))
    assert int(msgs.sum()) > 0


def test_sync_respects_partition():
    n = 8
    p = SyncParams(n_nodes=n, peers_per_round=2)
    base = C.pack(jnp.ones((n, 4), jnp.int32), jnp.ones((n, 4), jnp.int32),
                  jnp.zeros((n, 4), jnp.int32))
    news = C.pack(jnp.ones((4,), jnp.int32), jnp.full((4,), 2, jnp.int32),
                  jnp.ones((4,), jnp.int32))
    rows = base.at[0].set(news)
    part = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(1)
    for t in range(20):
        rows, msgs = sync_step(rows, msgs, jax.random.fold_in(key, t), p,
                               partition_id=part, partition_active=jnp.array(True))
    got = np.asarray((rows == news[None, :]).all(axis=1))
    assert got[: n // 2].all()
    assert not got[n // 2 :].any()


def test_sync_is_monotone():
    # a sync round never loses information
    n = 16
    p = SyncParams(n_nodes=n)
    rows = C.pack(
        jax.random.randint(jax.random.PRNGKey(2), (n, 4), 0, 3),
        jax.random.randint(jax.random.PRNGKey(3), (n, 4), 1, 5),
        jax.random.randint(jax.random.PRNGKey(4), (n, 4), 0, 9),
    )
    msgs = jnp.zeros((n,), jnp.int32)
    new_rows, _ = sync_step(rows, msgs, jax.random.PRNGKey(5), p)
    assert bool(jnp.all(new_rows >= rows))


def test_bitmap_needs_matches_host_algebra():
    """Dense bitmap needs == exact compute_available_needs on the same facts."""
    V = 32
    head = 20
    ours_gaps = [(3, 5), (9, 9)]
    # build bitmaps: version v known iff not in a gap and <= head
    ours = np.zeros(V, dtype=bool)
    ours[1 : head + 1] = True
    for s, e in ours_gaps:
        ours[s : e + 1] = False
    theirs_head = 26
    theirs = np.zeros(V, dtype=bool)
    theirs[1 : theirs_head + 1] = True

    dense = np.asarray(bitmap_needs(jnp.array(ours), jnp.array(theirs)))
    dense_versions = set(np.nonzero(dense)[0].tolist())

    actor = ActorId.generate()
    our_state = SyncStateV1(actor_id=ActorId.generate())
    our_state.heads[actor] = Version(head)
    our_state.need[actor] = ours_gaps
    their_state = SyncStateV1(actor_id=ActorId.generate())
    their_state.heads[actor] = Version(theirs_head)
    needs = our_state.compute_available_needs(their_state)
    host_versions = set()
    for need in needs[actor]:
        assert need.kind == "full"
        s, e = need.versions
        host_versions.update(range(s, e + 1))
    assert dense_versions == host_versions


# -- seq-chunked reassembly kernel -------------------------------------


def test_bitmap_gaps_match_rangeset():
    """Dense missing-seq bitmap == RangeSet.gaps on the same facts."""
    from corrosion_tpu.models.sync import bitmap_gaps
    from corrosion_tpu.utils.ranges import RangeSet

    S = 48
    rng = np.random.default_rng(7)
    bits = rng.random(S) < 0.6
    held = RangeSet()
    for i in np.nonzero(bits)[0]:
        held.insert(int(i), int(i))
    gap_set = set()
    for s, e in held.gaps(0, S - 1):
        gap_set.update(range(s, e + 1))
    dense = np.asarray(bitmap_gaps(jnp.array(bits)))
    assert set(np.nonzero(dense)[0].tolist()) == gap_set


def test_seq_sync_serving_matches_rangeset_order_and_budget():
    """The kernel serves exactly the first budget*seqs_per_chunk needed
    seqs in ascending order — the dense twin of walking RangeSet gaps
    span by span with a session budget."""
    from corrosion_tpu.models.sync import SeqSyncParams, seq_sync_step
    from corrosion_tpu.utils.ranges import RangeSet

    S = 40
    p = SeqSyncParams(
        n_nodes=2, n_seqs=S, peers_per_round=1,
        seqs_per_chunk=4, chunk_budget=2, loss=0.0,
    )
    rng = np.random.default_rng(3)
    server = rng.random(S) < 0.7
    client = server & (rng.random(S) < 0.3)  # client holds a subset
    bits = jnp.stack([jnp.array(client), jnp.array(server)])
    msgs = jnp.zeros((2,), jnp.int32)

    # with n=2 every peer pick is the other node
    new_bits, new_msgs = seq_sync_step(bits, msgs, jax.random.PRNGKey(0), p)

    # host-side: needs = server's spans minus client's, walked in order
    have = RangeSet()
    for i in np.nonzero(np.asarray(server) & ~np.asarray(client))[0]:
        have.insert(int(i), int(i))
    wanted = [i for s, e in have.spans() for i in range(s, e + 1)]
    expect = set(wanted[: p.chunk_budget * p.seqs_per_chunk])

    got = set(np.nonzero(np.asarray(new_bits[0]) & ~np.asarray(client))[0].tolist())
    assert got == expect
    # the server paid for ceil(|served|/spc) chunks plus half a handshake
    n_chunks = -(-len(expect) // p.seqs_per_chunk)
    assert int(new_msgs[1]) >= n_chunks


def test_seq_sync_out_of_order_hole_heals():
    """A dropped chunk while later chunks land leaves a hole (out-of-
    order arrival); subsequent rounds recompute needs from the bitmap
    and heal it."""
    from corrosion_tpu.models.sync import SeqSyncParams, seq_sync_step

    S = 32
    p = SeqSyncParams(
        n_nodes=2, n_seqs=S, peers_per_round=1,
        seqs_per_chunk=4, chunk_budget=8, loss=0.5,
    )
    full = jnp.ones((S,), bool)
    empty = jnp.zeros((S,), bool)

    hole_seen = False
    for seed in range(32):
        bits = jnp.stack([empty, full])
        msgs = jnp.zeros((2,), jnp.int32)
        bits1, _ = seq_sync_step(bits, msgs, jax.random.PRNGKey(seed), p)
        got = np.asarray(bits1[0])
        if got.any() and not got.all():
            # some chunk landed, some dropped: is there a hole — a held
            # seq AFTER a missing one?
            first_missing = int((~got).argmax())
            if got[first_missing:].any():
                hole_seen = True
                break
    assert hole_seen, "no out-of-order hole in 32 seeds (loss model broken?)"

    # heal: keep syncing, bits must be monotone and reach full
    key = jax.random.PRNGKey(seed)
    prev = bits1
    for t in range(64):
        nxt, msgs = seq_sync_step(prev, msgs, jax.random.fold_in(key, t), p)
        assert bool(jnp.all(nxt >= prev))  # never forgets a seq
        prev = nxt
        if bool(prev.all()):
            break
    assert bool(prev.all())


def test_anti_entropy_sim_converges():
    from corrosion_tpu.sim import AntiEntropyConfig, run_anti_entropy_seeds

    cfg = AntiEntropyConfig(
        n_nodes=256, n_seqs=32, loss=0.1, max_ticks=96, chunk_ticks=8
    )
    s = run_anti_entropy_seeds(cfg, n_seeds=4, seed=0)
    assert s["converged_frac"] == 1.0
    assert s["ticks_p99"] < 96
    assert s["msgs_per_node_mean"] > 0
