import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.models.sync import SyncParams, bitmap_needs, sync_step
from corrosion_tpu.ops.keys import DEFAULT_CODEC as C
from corrosion_tpu.types import ActorId, SyncStateV1, Version


def test_sync_heals_isolated_node():
    n = 8
    p = SyncParams(n_nodes=n, peers_per_round=2)
    base = C.pack(jnp.ones((n, 4), jnp.int32), jnp.ones((n, 4), jnp.int32),
                  jnp.zeros((n, 4), jnp.int32))
    news = C.pack(jnp.ones((4,), jnp.int32), jnp.full((4,), 2, jnp.int32),
                  jnp.ones((4,), jnp.int32))
    # everyone but node 3 already has the news
    rows = jnp.tile(news, (n, 1)).at[3].set(base[3])
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(0)
    for t in range(6):
        rows, msgs = sync_step(rows, msgs, jax.random.fold_in(key, t), p)
        if bool(jnp.all(rows == news[None, :])):
            break
    assert bool(jnp.all(rows == news[None, :]))
    assert int(msgs.sum()) > 0


def test_sync_respects_partition():
    n = 8
    p = SyncParams(n_nodes=n, peers_per_round=2)
    base = C.pack(jnp.ones((n, 4), jnp.int32), jnp.ones((n, 4), jnp.int32),
                  jnp.zeros((n, 4), jnp.int32))
    news = C.pack(jnp.ones((4,), jnp.int32), jnp.full((4,), 2, jnp.int32),
                  jnp.ones((4,), jnp.int32))
    rows = base.at[0].set(news)
    part = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    msgs = jnp.zeros((n,), jnp.int32)
    key = jax.random.PRNGKey(1)
    for t in range(20):
        rows, msgs = sync_step(rows, msgs, jax.random.fold_in(key, t), p,
                               partition_id=part, partition_active=jnp.array(True))
    got = np.asarray((rows == news[None, :]).all(axis=1))
    assert got[: n // 2].all()
    assert not got[n // 2 :].any()


def test_sync_is_monotone():
    # a sync round never loses information
    n = 16
    p = SyncParams(n_nodes=n)
    rows = C.pack(
        jax.random.randint(jax.random.PRNGKey(2), (n, 4), 0, 3),
        jax.random.randint(jax.random.PRNGKey(3), (n, 4), 1, 5),
        jax.random.randint(jax.random.PRNGKey(4), (n, 4), 0, 9),
    )
    msgs = jnp.zeros((n,), jnp.int32)
    new_rows, _ = sync_step(rows, msgs, jax.random.PRNGKey(5), p)
    assert bool(jnp.all(new_rows >= rows))


def test_bitmap_needs_matches_host_algebra():
    """Dense bitmap needs == exact compute_available_needs on the same facts."""
    V = 32
    head = 20
    ours_gaps = [(3, 5), (9, 9)]
    # build bitmaps: version v known iff not in a gap and <= head
    ours = np.zeros(V, dtype=bool)
    ours[1 : head + 1] = True
    for s, e in ours_gaps:
        ours[s : e + 1] = False
    theirs_head = 26
    theirs = np.zeros(V, dtype=bool)
    theirs[1 : theirs_head + 1] = True

    dense = np.asarray(bitmap_needs(jnp.array(ours), jnp.array(theirs)))
    dense_versions = set(np.nonzero(dense)[0].tolist())

    actor = ActorId.generate()
    our_state = SyncStateV1(actor_id=ActorId.generate())
    our_state.heads[actor] = Version(head)
    our_state.need[actor] = ours_gaps
    their_state = SyncStateV1(actor_id=ActorId.generate())
    their_state.heads[actor] = Version(theirs_head)
    needs = our_state.compute_available_needs(their_state)
    host_versions = set()
    for need in needs[actor]:
        assert need.kind == "full"
        s, e = need.versions
        host_versions.update(range(s, e + 1))
    assert dense_versions == host_versions
