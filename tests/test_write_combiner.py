"""Group-commit write combining: parity, atomicity, ordering, fanout.

The combiner (``agent/writes.py`` + ``runtime._execute_write_group``,
docs/writes.md) must be observationally equivalent to the
per-transaction oracle: converged data, clock/cl state, bookkeeping,
version assignment, and one broadcast changeset per client transaction.
The randomized suite replays each concurrent run's committed batches in
version order through the oracle and compares full state dumps.
"""

from __future__ import annotations

import asyncio
import random
import threading

import pytest

from corrosion_tpu.agent.testing import (
    launch_test_agent,
    make_offline_agent,
    wait_for,
)
from corrosion_tpu.agent.writes import WriteRequest, has_tx_control


def _close(agent) -> None:
    if agent._wbcast_pool is not None:
        agent._wbcast_pool.shutdown(wait=True)
        agent._wbcast_pool = None
    agent.storage.close()


def _state_dump(agent) -> dict:
    """Deterministic converged-state snapshot: table data, clock/cl
    stamps, bookkeeping rows (ts excluded — HLC wall time differs
    between runs), and the in-memory version ledger."""
    conn = agent.storage.conn
    dump: dict = {}
    for t in agent.storage.tables:
        dump[t] = sorted(conn.execute(f'SELECT * FROM "{t}"').fetchall())
        dump[t + "_clock"] = sorted(
            (bytes(row[0]), *row[1:])
            for row in conn.execute(
                f'SELECT pk, cid, col_version, db_version, seq,'
                f' site_ordinal FROM "{t}__corro_clock"'
            ).fetchall()
        )
        dump[t + "_cl"] = sorted(
            (bytes(row[0]), *row[1:])
            for row in conn.execute(
                f'SELECT pk, cl, db_version, seq, site_ordinal, sentinel'
                f' FROM "{t}__corro_cl"'
            ).fetchall()
        )
    dump["bookkeeping"] = sorted(
        conn.execute(
            "SELECT start_version, end_version, db_version, last_seq "
            "FROM __corro_bookkeeping WHERE actor_id=?",
            (agent.actor_id,),
        ).fetchall()
    )
    bv = agent.bookie.for_actor(agent.actor_id)
    dump["versions"] = sorted(
        (v, dbv, ls) for v, (dbv, ls) in bv.versions.items()
    )
    dump["max_version"] = bv.last()
    return dump


def _random_batch(rng: random.Random, tag: str):
    """One client transaction: 1-3 statements over a small id space so
    concurrent runs genuinely contend; never statement-level failing."""
    stmts = []
    for _ in range(rng.randint(1, 3)):
        kind = rng.random()
        rid = rng.randint(0, 20)
        if kind < 0.6:
            stmts.append((
                "INSERT INTO tests (id, text) VALUES (?, ?) "
                "ON CONFLICT(id) DO UPDATE SET text=excluded.text",
                (rid, f"{tag}-{rng.randint(0, 999)}"),
            ))
        elif kind < 0.8:
            stmts.append((
                "UPDATE tests SET text=? WHERE id=?",
                (f"{tag}-u{rng.randint(0, 999)}", rid),
            ))
        elif kind < 0.95:
            stmts.append(("DELETE FROM tests WHERE id=?", (rid,)))
        else:
            # changeless: matches no row, consumes no version
            stmts.append(("UPDATE tests SET text='x' WHERE id=-1", ()))
    return stmts


@pytest.mark.parametrize("seed", range(8))
def test_concurrent_writer_parity_vs_sequential_oracle(seed):
    """N threads x M transactions through the combiner, then the SAME
    batches replayed in version (commit) order through the per-tx
    oracle: every byte of converged state must match, and version
    assignment must be gapless and submission-ordered."""
    n_threads, n_tx = 4, 6
    combined = make_offline_agent(write_group_commit=True)
    oracle = make_offline_agent(write_group_commit=False)
    try:
        committed = {}  # version -> statements
        errors = []
        bar = threading.Barrier(n_threads)

        def worker(t: int) -> None:
            rng = random.Random((seed << 8) | t)
            bar.wait()
            for i in range(n_tx):
                stmts = _random_batch(rng, f"s{seed}t{t}i{i}")
                try:
                    res = combined.execute_transaction(stmts)
                except Exception as e:  # no batch here may fail
                    errors.append(e)
                    return
                if res["version"] is not None:
                    committed[res["version"]] = stmts

        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # gapless, submission-ordered assignment
        versions = sorted(committed)
        assert versions == list(range(1, len(versions) + 1))
        bv = combined.bookie.for_actor(combined.actor_id)
        assert bv.last() == len(versions)
        assert bv.contains_range(1, bv.last())
        # sequential replay in commit order on the oracle
        for v in versions:
            res = oracle.execute_transaction(committed[v])
            assert res["version"] == v
        assert _state_dump(combined) == _state_dump(oracle)
    finally:
        _close(combined)
        _close(oracle)


def test_savepoint_atomicity_with_injected_failures():
    """A failing batch inside a group rolls back to ITS savepoint and
    fails only its caller; the surrounding batches commit with gapless,
    submission-ordered versions."""
    a = make_offline_agent()
    try:
        reqs = [
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (1, "a"))]),
            # NOT NULL violation: text has no NULL-accepting default path
            WriteRequest([
                ("INSERT INTO tests (id, text) VALUES (?, ?)", (2, "b")),
                ("INSERT INTO tests (id, text) VALUES (?, NULL)", (3,)),
            ]),
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (4, "c"))]),
            # changeless: no version consumed
            WriteRequest([("UPDATE tests SET text='x' WHERE id=-1", ())]),
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (5, "d"))]),
        ]
        a._execute_write_group(reqs)
        assert reqs[0].result["version"] == 1
        assert reqs[1].result is None
        assert type(reqs[1].error).__name__ == "IntegrityError"
        assert reqs[2].result["version"] == 2
        assert reqs[3].result["version"] is None
        assert reqs[4].result["version"] == 3
        # the failed batch's FIRST statement rolled back with it
        _, rows = a.storage.read_query("SELECT id FROM tests ORDER BY id")
        assert [r[0] for r in rows] == [1, 4, 5]
        bv = a.bookie.for_actor(a.actor_id)
        assert bv.last() == 3 and bv.contains_range(1, 3)
        # persisted bookkeeping matches memory (restart = resume)
        _, rows = a.storage.read_query(
            "SELECT COUNT(*) FROM __corro_bookkeeping "
            "WHERE actor_id=? AND end_version IS NULL",
            (a.actor_id,),
        )
        assert rows[0][0] == 3
    finally:
        _close(a)


def test_group_abort_falls_back_to_per_tx():
    """A statement that kills the OUTER transaction (here a bare
    ROLLBACK, which screening normally keeps out of groups) aborts the
    group; every other batch replays through the per-tx oracle path and
    still commits — the aborting caller alone gets the error."""
    a = make_offline_agent()
    try:
        reqs = [
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (1, "a"))]),
            WriteRequest([("ROLLBACK", ())]),
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (2, "b"))]),
        ]
        a._execute_write_group(reqs)
        assert reqs[0].result["version"] == 1 and reqs[0].error is None
        assert reqs[1].error is not None and reqs[1].result is None
        assert reqs[2].result["version"] == 2 and reqs[2].error is None
        _, rows = a.storage.read_query("SELECT id FROM tests ORDER BY id")
        assert [r[0] for r in rows] == [1, 2]
        assert a.metrics.get_counter(
            "corro_write_group_fallbacks_total", reason="abort") == 1
        bv = a.bookie.for_actor(a.actor_id)
        assert bv.contains_range(1, bv.last()) and bv.last() == 2
    finally:
        _close(a)


def test_tx_control_statements_take_oracle_path():
    """Transaction-control/file-level SQL is screened out of groups —
    it runs the per-tx oracle (counted) with unchanged results — and a
    comment prefix cannot smuggle it past the screen."""
    assert has_tx_control(["COMMIT"])
    assert has_tx_control([("pragma user_version", ())])
    assert has_tx_control(["/* x */ COMMIT"])
    assert has_tx_control(["-- c\nROLLBACK"])
    assert has_tx_control(["  /* a */ -- b\n  /* c */ BEGIN"])
    assert not has_tx_control([("INSERT INTO t VALUES (1)", ())])
    assert not has_tx_control(["/* COMMIT */ INSERT INTO t VALUES (1)"])
    a = make_offline_agent()
    try:
        a.execute_transaction(["PRAGMA user_version"])
        assert a.metrics.get_counter(
            "corro_write_group_fallbacks_total", reason="stmt") == 1
        # and a normal write afterwards still combines fine
        res = a.execute_transaction([
            ("INSERT INTO tests (id, text) VALUES (1, 'x')", ())
        ])
        assert res["version"] == 1
    finally:
        _close(a)


def test_on_conn_hook_contract_in_groups():
    """The cancellation hook sees the RW connection while ITS batch
    executes under the lock, then None — same contract as the oracle."""
    a = make_offline_agent()
    try:
        calls = []
        req = WriteRequest(
            [("INSERT INTO tests (id, text) VALUES (1, 'x')", ())],
            on_conn=lambda c: calls.append(c),
        )
        a._execute_write_group([req])
        assert req.error is None
        assert calls[0] is a.storage.conn and calls[1] is None
    finally:
        _close(a)


def test_hostile_mid_group_commit_never_double_applies():
    """Belt-and-braces for a statement that slips past tx-control
    screening and COMMITS the outer transaction mid-group (driven
    directly through _execute_write_group to bypass the screen): the
    already-durable prefix is finished in place — version assigned,
    bookkeeping persisted, caller told success — NOT replayed (which
    would double-apply), while later batches fall back per-tx."""
    a = make_offline_agent()
    try:
        reqs = [
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (1, "a"))]),
            WriteRequest(["/* smuggled */ COMMIT"]),
            WriteRequest([(
                "INSERT INTO tests (id, text) VALUES (?, ?)", (2, "b"))]),
        ]
        a._execute_write_group(reqs)
        # batch 0 committed durably via the hostile COMMIT and was
        # recovered, not replayed: exactly one row, version 1, success
        assert reqs[0].error is None and reqs[0].result["version"] == 1
        assert reqs[1].error is not None and reqs[1].result is None
        assert reqs[2].error is None and reqs[2].result["version"] == 2
        _, rows = a.storage.read_query(
            "SELECT id, COUNT(*) FROM tests GROUP BY id ORDER BY id")
        assert [tuple(r) for r in rows] == [(1, 1), (2, 1)]
        assert a.metrics.get_counter(
            "corro_write_group_hostile_commits_total") == 1
        # recovered version is advertised: memory and durable
        # bookkeeping agree, gapless
        bv = a.bookie.for_actor(a.actor_id)
        assert bv.last() == 2 and bv.contains_range(1, 2)
        _, rows = a.storage.read_query(
            "SELECT COUNT(*) FROM __corro_bookkeeping "
            "WHERE actor_id=? AND end_version IS NULL", (a.actor_id,))
        assert rows[0][0] == 2
    finally:
        _close(a)


def test_leader_death_resolves_inflight_group():
    """If a BaseException escapes the group executor (belt-and-braces:
    interpreter shutdown, KeyboardInterrupt), the already-popped group's
    members must still resolve — a stranded caller would block its
    handler thread forever — and the combiner must elect a fresh leader
    for the next submit."""
    a = make_offline_agent()
    try:
        orig = a._execute_write_group

        def boom(reqs):
            raise KeyboardInterrupt("injected leader death")

        a._execute_write_group = boom
        with pytest.raises(KeyboardInterrupt):
            a.execute_transaction([
                ("INSERT INTO tests (id, text) VALUES (1, 'x')", ())
            ])
        a._execute_write_group = orig
        # no stuck leadership claim: the next write combines normally
        res = a.execute_transaction([
            ("INSERT INTO tests (id, text) VALUES (2, 'y')", ())
        ])
        assert res["version"] == 1
    finally:
        _close(a)


def test_no_wbcast_pool_rebirth_after_stop():
    """A write completing concurrently with stop() must not lazily
    recreate the broadcast worker pool after teardown — that leaked a
    thread reading closed storage.  Post-stop dispatches drop."""
    async def main():
        a = await launch_test_agent()
        a.execute_transaction([
            ("INSERT INTO tests (id, text) VALUES (1, 'x')", ())
        ])
        await a.stop()
        assert a._wbcast_pool is None
        assert a._wbcast_executor() is None
        # the late-dispatch path a racing writer would take: no-op
        a._dispatch_local_broadcast([(2, 2, 0, 0)])
        assert a._wbcast_pool is None

    asyncio.run(main())


def test_group_emits_one_broadcast_changeset_per_transaction():
    """Subscription/broadcast parity: a combined group still fans out
    one complete changeset per client transaction, in version order,
    through ``on_change`` — deterministically via a direct group, then
    under real concurrent writers."""
    async def main():
        a = await launch_test_agent(subs_enabled=False)
        got = []
        a.on_change = got.append
        try:
            # deterministic group of 3
            reqs = [
                WriteRequest([(
                    "INSERT INTO tests (id, text) VALUES (?, ?)",
                    (i, f"v{i}"))])
                for i in range(3)
            ]
            await asyncio.get_running_loop().run_in_executor(
                None, a._execute_write_group, reqs
            )
            await wait_for(lambda: len(got) >= 3, timeout=10)
            assert [int(cv.changeset.version) for cv in got] == [1, 2, 3]
            assert all(
                cv.changeset.is_full and cv.changeset.is_complete()
                for cv in got
            )
            # concurrent writers: one changeset per committed version
            loop = asyncio.get_running_loop()

            def writer(w: int):
                for i in range(4):
                    a.execute_transaction([(
                        "INSERT INTO tests (id, text) VALUES (?, ?)",
                        (100 + w * 10 + i, "y"),
                    )])

            await asyncio.gather(*[
                loop.run_in_executor(None, writer, w) for w in range(4)
            ])
            await wait_for(lambda: len(got) >= 3 + 16, timeout=10)
            assert sorted(
                int(cv.changeset.version) for cv in got
            ) == list(range(1, 20))
        finally:
            await a.stop()

    asyncio.run(main())
