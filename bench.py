#!/usr/bin/env python
"""North-star benchmark: simulate 100k-node epidemic convergence.

BASELINE.json config #5: 100k nodes, 5% message loss, 2-way partition that
heals mid-run, gossip fanout + periodic anti-entropy; metric = wall time to
simulate the cluster to full CRDT convergence, with p99 convergence ticks
and msgs/node from vmapped parallel universes.

Target (BASELINE.json): <60 s on a TPU v5e-8.  This runs on whatever the
default JAX backend offers (one v5e chip in CI), so beating 60 s here beats
the 8-chip target with 1/8th the silicon.

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--seeds", type=int, default=32)
    ap.add_argument("--rows", type=int, default=8)
    ap.add_argument("--check", action="store_true",
                    help="fast correctness pass (small N)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    if args.check:
        args.nodes, args.seeds = 4096, 8

    from corrosion_tpu.sim import EpidemicConfig, run_epidemic_seeds

    cfg = EpidemicConfig(
        n_nodes=args.nodes,
        n_rows=args.rows,
        fanout_ring0=2,
        fanout_global=2,
        ring0_size=256,
        max_transmissions=8,
        loss=0.05,
        partition_blocks=2,
        heal_tick=12,
        sync_interval=8,
        sync_peers=1,
        max_ticks=192,
        chunk_ticks=16,
    )

    # warmup run compiles every chunk shape; the measured run reuses them
    t0 = time.perf_counter()
    warm = run_epidemic_seeds(cfg, n_seeds=args.seeds, seed=1)
    compile_and_first = time.perf_counter() - t0

    stats = run_epidemic_seeds(cfg, n_seeds=args.seeds, seed=0)

    if stats["converged_frac"] < 1.0:
        safe = {
            k: (None if isinstance(v, float) and not (v == v and abs(v) != float("inf")) else v)
            for k, v in stats.items()
        }
        print(json.dumps({"error": "did not converge", **safe}), file=sys.stderr)

    baseline_s = 60.0  # BASELINE.json north-star budget on v5e-8
    value = round(stats["wall_s"], 3)
    ticks_p99 = stats["ticks_p99"]
    out = {
        "metric": f"epidemic_convergence_sim_{args.nodes//1000}k_nodes_wall",
        "value": value,
        "unit": "s",
        "vs_baseline": round(baseline_s / max(value, 1e-9), 2),
        # inf (a seed never converged) is not valid JSON; emit null instead
        "ticks_p99": None if not (ticks_p99 < float("inf")) else ticks_p99,
        "msgs_per_node_mean": round(stats["msgs_per_node_mean"], 1),
        "converged_frac": stats["converged_frac"],
        "n_seeds": args.seeds,
        "compile_s": round(compile_and_first - stats["wall_s"], 1),
    }
    if args.verbose:
        print("warmup:", warm, file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
