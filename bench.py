#!/usr/bin/env python
"""North-star benchmark: every BASELINE.json config, one JSON line each.

The metric (BASELINE.json) is "p99 convergence time + msgs/node vs
cluster size N".  Configs:

  #1 corro-devcluster 3-node, single LWW table — REAL agents on
     loopback (gossip + sync + CRDT storage), wall-clock convergence of
     concurrent conflicting writes;
  #2 64-node SWIM membership churn — failure-detection + rejoin
     propagation latency from the vmapped SWIM kernel;
  #3 1k-node broadcast fanout + LWW merge convergence (gossip only);
  #4 10k-node periodic anti-entropy sync (subset peer selection,
     broadcast disabled: knowledge moves only through sync rounds);
  #5 100k-node epidemic broadcast, 5% loss + partition heal (the
     headline: <60 s budget on a TPU v5e-8).

Emits one JSON line per config; the LAST line is the headline (config
 #5 wall time vs the 60 s budget) carrying the full sweep under
"configs" and the msgs/node-vs-N series under "msgs_per_node_vs_n".
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: the first run pays compile,
    later runs (same chip + jax version) reuse it.  Must be set via
    jax.config (the env-var path leaves the cache uninitialized for
    writes on this backend)."""
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
    )
    # every compile matters here: the axon tunnel adds ~0.5 s of fixed
    # cost even to trivial eager ops, and there are dozens of them
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _sanitize(obj):
    """null out non-finite floats recursively (inf/nan are not JSON)."""
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, float) and not (obj == obj and abs(obj) != float("inf")):
        return None
    return obj


def _emit(line: dict) -> None:
    print(json.dumps(_sanitize(line)), flush=True)


def _committed_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- apply-path microbenchmark (bench.py --apply) ----------------------


def _apply_bench_changes(n: int, site: bytes, col_version: int,
                         row_offset: int = 0):
    """``n`` cell changes over ``n // 4`` rows x 4 cells — the shape of
    a sync-driven backfill (many rows, few cells each).  ``row_offset``
    shifts the pk range so the device-arm flood scenario can make every
    wave touch FRESH rows."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
    from corrosion_tpu.types.change import Change

    changes = []
    seq = 0
    for r in range(row_offset, row_offset + max(1, n // 4)):
        pk = pack_values([r])
        for cid in ("a", "b", "c", "d"):
            changes.append(Change(
                table="bench", pk=pk, cid=cid,
                val=f"v{col_version}-{r}-{cid}",
                col_version=col_version,
                db_version=CrsqlDbVersion(col_version),
                seq=CrsqlSeq(seq), site_id=site, cl=1,
            ))
            seq += 1
            if len(changes) >= n:
                return changes
    return changes


_APPLY_AB_SCHEMA = """
CREATE TABLE IF NOT EXISTS bench (
  id INTEGER NOT NULL PRIMARY KEY,
  a TEXT, b TEXT, c TEXT, d TEXT
);
"""


def _apply_ingest_once(d: str, n_changes: int, tag: str,
                       cfg_overrides=None) -> float:
    """Agent-level ingest throughput (changes/s): ``n_changes`` cell
    changes as complete single-version changesets from one remote
    actor, fed through ``Agent._apply_batch`` in bounded batches — the
    layer the provenance plane instruments.  The storage-level apply
    the headline measures sits BELOW the plane and never executes it."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ActorId, ChangeSource, ChangeV1, Changeset
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
    from corrosion_tpu.types.change import Change

    site = b"\x51" * 16
    adir = os.path.join(d, f"ingest{tag}")
    os.makedirs(adir, exist_ok=True)
    agent = make_offline_agent(
        tmpdir=adir, schema=_APPLY_AB_SCHEMA, **(cfg_overrides or {})
    )
    try:
        cvs = []
        total = 0
        v = 0
        while total < n_changes:
            v += 1
            pk = pack_values([v])
            changes = []
            for seq, cid in enumerate(("a", "b", "c", "d")):
                changes.append(Change(
                    table="bench", pk=pk, cid=cid, val=f"v-{v}-{cid}",
                    col_version=1, db_version=CrsqlDbVersion(v),
                    seq=CrsqlSeq(seq), site_id=site, cl=1,
                ))
                total += 1
                if total >= n_changes:
                    break
            last = CrsqlSeq(len(changes) - 1)
            cvs.append(ChangeV1(
                actor_id=ActorId(site),
                changeset=Changeset.full(
                    Version(v), changes, (CrsqlSeq(0), last), last,
                    agent.clock.new_timestamp(),
                ),
            ))
        t0 = time.perf_counter()
        for i in range(0, len(cvs), 64):
            agent._apply_batch(
                [(cv, ChangeSource.SYNC) for cv in cvs[i:i + 64]]
            )
        wall = time.perf_counter() - t0
        return n_changes / max(wall, 1e-9)
    finally:
        agent.storage.close()


def _apply_overhead_ab(n_changes: int, reps: int = 5,
                       committed=None, measured=None,
                       max_regression: float = 0.05) -> dict:
    """Paired in-run A/B of the observability plane's ingest cost,
    mirroring ``_write_overhead_ab``: plane off vs on in temporally-
    adjacent pairs (arm order alternating per pair), gated on the
    MEDIAN per-pair ratio.  The host's throughput is bimodal (a
    virtualized box drifts between full-core and shared-core modes),
    which defeats both best-of-N (one lucky spike in one arm skews the
    ratio of bests) and any cross-run comparison — but the two runs of
    an adjacent pair almost always land in the SAME mode, so per-pair
    ratios are stable and their median rejects the rare pair that
    straddles a mode switch."""
    import statistics
    import tempfile

    pairs = []
    with tempfile.TemporaryDirectory(prefix="corro-apply-ab-") as d:
        for rep in range(reps):
            arms = (("off", _PLANE_OFF), ("on", None))
            if rep % 2:
                arms = arms[::-1]
            cps = {}
            for arm, over in arms:
                cps[arm] = _apply_ingest_once(
                    d, n_changes, f"-{arm}{rep}", cfg_overrides=over
                )
            pairs.append({
                "off_changes_per_s": round(cps["off"], 1),
                "on_changes_per_s": round(cps["on"], 1),
                "ratio": round(cps["on"] / max(cps["off"], 1e-9), 4),
            })
    ratio = statistics.median(p["ratio"] for p in pairs)
    gate = {
        "method": (
            f"paired in-run A/B, {reps} adjacent off/on pairs of "
            "agent-level ingest (_apply_batch, SYNC source) at the "
            "headline change count (arm order alternating), median "
            "per-pair ratio; plane = provenance (the only knob live "
            "at this layer: the offline agent never start()s a stall "
            "probe, and SYNC ingest does not encode traced uni "
            "frames — those costs are covered by the write-path A/B)"
        ),
        "n_changes": n_changes,
        "pairs": pairs,
        "ratio": round(ratio, 4),
        "max_regression": max_regression,
        "pass": bool(ratio >= 1.0 - max_regression),
    }
    if committed is not None and measured is not None:
        # cross-run context only (host drift dwarfs the plane's cost)
        gate["committed"] = committed
        gate["committed_ratio"] = round(measured / committed, 4)
    return gate


_SIG_AB_SITE = b"\x52" * 16


def _sig_build_payloads(n_changes: int):
    """The A/B's shared corpus: complete single-version broadcast
    changesets from one remote actor, pre-encoded BOTH ways — the
    pre-signing traced (v1) envelope and the signed (v2) envelope.
    Built once per A/B run: signing 2.5k payloads costs seconds of
    big-int crypto, and doing it inside each rep would wrap every
    timed window in a different thermal/scheduler state."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.agent.runtime import sig_message
    from corrosion_tpu.bridge import speedy
    from corrosion_tpu.types import ActorId, ChangeV1, Changeset
    from corrosion_tpu.types.actor import ClusterId
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq, Version
    from corrosion_tpu.types.change import Change
    from corrosion_tpu.types.crypto import seed_keypair, sign
    from corrosion_tpu.types.hlc import HLClock
    from corrosion_tpu.types.payload import BroadcastV1, UniPayload

    site = _SIG_AB_SITE
    secret, pub = seed_keypair(b"sig-ab-origin")
    clock = HLClock()
    v1s, v2s = [], []
    total = 0
    v = 0
    while total < n_changes:
        v += 1
        pk = pack_values([v])
        changes = []
        for seq, cid in enumerate(("a", "b", "c", "d")):
            changes.append(Change(
                table="bench", pk=pk, cid=cid, val=f"v-{v}-{cid}",
                col_version=1, db_version=CrsqlDbVersion(v),
                seq=CrsqlSeq(seq), site_id=site, cl=1,
            ))
            total += 1
            if total >= n_changes:
                break
        last = CrsqlSeq(len(changes) - 1)
        cs = Changeset.full(
            Version(v), changes, (CrsqlSeq(0), last), last,
            clock.new_timestamp(),
        )
        cv = ChangeV1(actor_id=ActorId(site), changeset=cs)
        classic = speedy.encode_uni_payload(UniPayload(
            broadcast=BroadcastV1(change=cv),
            cluster_id=ClusterId(0),
        ))
        sig = sign(secret, sig_message(site, cs))
        v1s.append(speedy.encode_traced_uni(classic, None, 0))
        v2s.append(speedy.encode_signed_uni(classic, None, 0, sig))
    return v1s, v2s, pub


def _sig_ingest_run(d: str, payloads, n_changes: int, tag: str,
                    signed_on: bool, pub: bytes) -> float:
    """One timed arm: raw payloads through ``Agent._apply_batch`` —
    the layer where the envelope decode, the digest+signature
    bookkeeping and the bounded spot check actually run.  ``signed_on``
    = signed (v2) envelopes + a populated trust directory + spot
    checks at the campaign posture; off = the pre-signing traced
    envelope with no keys (the default wire)."""
    from corrosion_tpu.agent.testing import make_offline_agent

    adir = os.path.join(d, f"sig{tag}")
    os.makedirs(adir, exist_ok=True)
    overrides = {}
    if signed_on:
        overrides = dict(
            sig_pubkeys={_SIG_AB_SITE: pub},
            sig_spot_check_rate=0.05,  # the campaign posture
        )
    agent = make_offline_agent(
        tmpdir=adir, schema=_APPLY_AB_SCHEMA, **overrides
    )
    try:
        peer = ("bench-peer", 1)
        t0 = time.perf_counter()
        for i in range(0, len(payloads), 64):
            agent._apply_batch([
                ((p, peer), None) for p in payloads[i:i + 64]
            ])
        wall = time.perf_counter() - t0
        # the A/B is only honest if the on-arm actually carried live
        # signatures through the verdict machinery
        if signed_on:
            assert agent._equiv_sigs, "signed arm recorded no sigs"
        return n_changes / max(wall, 1e-9)
    finally:
        agent.storage.close()


def _sig_overhead_ab(n_changes: int, reps: int = 7,
                     max_regression: float = 0.05) -> dict:
    """Paired in-run A/B of signed attribution's ingest cost, same
    pairing/median discipline as ``_apply_overhead_ab``: signing off
    (the pre-PR wire + no keys) vs on (signed envelopes, signature
    bookkeeping, spot checks at campaign posture) in temporally-
    adjacent pairs, gated on the median per-pair ratio ≥ 0.95."""
    import statistics
    import tempfile

    v1s, v2s, pub = _sig_build_payloads(n_changes)
    pairs = []
    with tempfile.TemporaryDirectory(prefix="corro-sig-ab-") as d:
        # one unrecorded warmup per arm: first-run costs (module
        # imports, allocator warmup) must not skew a recorded pair
        _sig_ingest_run(d, v1s[:256], 1024, "-warm-off", False, pub)
        _sig_ingest_run(d, v2s[:256], 1024, "-warm-on", True, pub)
        for rep in range(reps):
            arms = (("off", False), ("on", True))
            if rep % 2:
                arms = arms[::-1]
            cps = {}
            for arm, on in arms:
                cps[arm] = _sig_ingest_run(
                    d, v2s if on else v1s, n_changes,
                    f"-{arm}{rep}", on, pub,
                )
            pairs.append({
                "off_changes_per_s": round(cps["off"], 1),
                "on_changes_per_s": round(cps["on"], 1),
                "ratio": round(cps["on"] / max(cps["off"], 1e-9), 4),
            })
    ratio = statistics.median(p["ratio"] for p in pairs)
    return {
        "method": (
            f"paired in-run A/B, {reps} adjacent off/on pairs of "
            "agent-level RAW-payload ingest (_apply_batch, BROADCAST "
            "source) at the headline change count (arm order "
            "alternating), median per-pair ratio; on = signed v2 "
            "envelopes + trust directory + digest/signature "
            "bookkeeping + spot checks (rate 0.05, interval-bounded), "
            "off = the pre-signing traced envelope with no keys"
        ),
        "n_changes": n_changes,
        "pairs": pairs,
        "ratio": round(ratio, 4),
        "max_regression": max_regression,
        "pass": bool(ratio >= 1.0 - max_regression),
    }


def _apply_state_digest(db) -> str:
    """Order-normalized digest of every piece of observable CRDT state
    — the in-bench parity witness between the apply arms."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for t in sorted(db.tables):
        q = t.replace('"', '""')
        h.update(repr(sorted(
            db.conn.execute(f'SELECT * FROM "{q}"').fetchall(),
            key=repr,
        )).encode())
        h.update(repr(sorted(db.conn.execute(
            f'SELECT pk, cid, col_version, db_version, seq, '
            f'site_ordinal FROM "{q}__corro_clock"').fetchall())).encode())
        h.update(repr(sorted(db.conn.execute(
            f'SELECT pk, cl, db_version, seq, site_ordinal, sentinel '
            f'FROM "{q}__corro_cl"').fetchall())).encode())
    # ordinal 1 is the node's OWN random id — only the interned remote
    # sites are part of the applied-state contract
    h.update(repr(db.conn.execute(
        "SELECT ordinal, site_id FROM __corro_sites WHERE ordinal > 1 "
        "ORDER BY ordinal"
    ).fetchall()).encode())
    return h.hexdigest()


def _apply_kernel_ab(n_changes: int, reps: int = 7,
                     max_regression: float = 0.10) -> dict:
    """Paired in-run A/B of the columnar merge kernel at the STORAGE
    layer (the PR 6 pairing/median discipline): dict-replay (kernel
    off) vs columnar (kernel on) batched applies of the same cold
    stream in temporally-adjacent pairs, arm order alternating, gated
    on the median per-pair ratio — plus a per-pair state-digest parity
    assert, so a speedup over divergent semantics can never read as a
    win.  The floor is 0.90 (not the observability planes' 0.95): the
    two arms are alternative merge IMPLEMENTATIONS whose product gate
    is the batched-vs-per-change headline, and on a CPU host the
    columnar path's encode cost sits within host noise of the dict
    replay — the kernel buys the shared sim/live winner-selection core
    and the accelerator-resident reduction form, and this gate proves
    it never costs more than 10% of the oracle's merge wall."""
    import statistics
    import tempfile

    from corrosion_tpu.agent.storage import CrConn

    site = b"\x42" * 16
    changes = _apply_bench_changes(n_changes, site, col_version=1)
    pairs = []
    parity = True

    def _arm_once(d, tag, columnar):
        db = CrConn(os.path.join(d, f"kab-{tag}.db"))
        try:
            db.columnar_merge = columnar
            db.columnar_merge_min = 0
            db.conn.execute(
                "CREATE TABLE IF NOT EXISTS bench ("
                " id INTEGER PRIMARY KEY NOT NULL, a, b, c, d)"
            )
            db.as_crr("bench")
            t0 = time.perf_counter()
            db.apply_changes_batched(changes)
            wall = time.perf_counter() - t0
            return wall, _apply_state_digest(db)
        finally:
            db.close()

    with tempfile.TemporaryDirectory(prefix="corro-apply-kab-") as d:
        # one unrecorded warmup per arm: first-use costs (numpy/ops
        # imports, allocator growth) must not skew a recorded pair
        _arm_once(d, "warm-off", False)
        _arm_once(d, "warm-on", True)
        for rep in range(reps):
            arms = (("off", False), ("on", True))
            if rep % 2:
                arms = arms[::-1]
            cps = {}
            digests = {}
            for arm, columnar in arms:
                # best-of-2 per arm: a scheduler preemption inside one
                # 70 ms apply would otherwise dominate the pair ratio;
                # symmetric across arms, so no directional bias
                w1, dig = _arm_once(d, f"{arm}{rep}a", columnar)
                w2, _ = _arm_once(d, f"{arm}{rep}b", columnar)
                cps[arm] = n_changes / max(min(w1, w2), 1e-9)
                digests[arm] = dig
            if digests["off"] != digests["on"]:
                parity = False
            pairs.append({
                "off_changes_per_s": round(cps["off"], 1),
                "on_changes_per_s": round(cps["on"], 1),
                "ratio": round(cps["on"] / max(cps["off"], 1e-9), 4),
            })
    ratio = statistics.median(p["ratio"] for p in pairs)
    return {
        "method": (
            f"paired in-run A/B, {reps} adjacent off/on pairs of "
            "storage-level batched apply at the headline change count "
            "(arm order alternating, one unrecorded warmup per arm, "
            "best-of-2 applies per recorded arm), median per-pair "
            "ratio; on = the columnar merge kernel (ops/merge.py "
            "segment reductions), off = the per-change dict-replay "
            "oracle; per-pair state digests asserted equal; floor "
            "0.90 — the arms are alternative merge implementations "
            "(the product gate is the batched-vs-per-change "
            "headline), and the kernel must never cost more than 10% "
            "of the oracle's apply wall"
        ),
        "n_changes": n_changes,
        "pairs": pairs,
        "ratio": round(ratio, 4),
        "parity": parity,
        "max_regression": max_regression,
        "pass": bool(parity and ratio >= 1.0 - max_regression),
    }


def _apply_stall_gate(n_changes: int, budget_ms: float = 50.0) -> dict:
    """Event-loop stall gate for the batched apply: the full stream
    applies in runtime-shaped chunks on executor threads (exactly how
    the apply workers hold the storage path) under a concurrent stall
    probe; the loop's worst scheduling gap must stay within budget."""
    import asyncio as _asyncio
    import tempfile

    from corrosion_tpu.agent.storage import CrConn

    site = b"\x42" * 16
    changes = _apply_bench_changes(n_changes, site, col_version=1)

    async def run(db):
        stats = {"max_stall_ms": 0.0}
        probe = _asyncio.ensure_future(_stall_probe(stats))
        loop = _asyncio.get_running_loop()
        try:
            for i in range(0, len(changes), 2048):
                await loop.run_in_executor(
                    None, db.apply_changes_batched,
                    changes[i : i + 2048],
                )
        finally:
            await _asyncio.sleep(0.02)  # let the probe sample the tail
            probe.cancel()
        return stats["max_stall_ms"]

    with tempfile.TemporaryDirectory(prefix="corro-apply-stall-") as d:
        db = CrConn(os.path.join(d, "stall.db"))
        try:
            db.conn.execute(
                "CREATE TABLE IF NOT EXISTS bench ("
                " id INTEGER PRIMARY KEY NOT NULL, a, b, c, d)"
            )
            db.as_crr("bench")
            max_stall = _asyncio.run(run(db))
        finally:
            db.close()
    return {
        "method": (
            "full cold stream applied in 2048-change chunks on "
            "executor threads (the apply-worker shape) under a "
            "concurrent 5 ms event-loop stall probe"
        ),
        "n_changes": n_changes,
        "max_stall_ms": round(max_stall, 2),
        "budget_ms": budget_ms,
        "pass": bool(max_stall <= budget_ms),
    }


def _apply_device_arm(n_changes: int, waves: int = 12,
                      committed_floor=None) -> dict:
    """Device-resident apply arm (docs/crdts.md "Device-resident
    apply") with an explicit cache-hit/invalidation model, two
    scenarios:

    - ``steady`` — steady-state broadcast over HOT keys: prefill the
      rows once, then ``waves`` superseding passes over the SAME rows,
      one batched apply per wave.  Consecutive waves hit the persistent
      clock cache, so the device arm skips the per-wave SQLite
      prefetch and coalesces the waves' flushes behind one barrier.
      This is the arm the floor gates: its speedup over the per-change
      oracle must beat the committed columnar cold headline.
    - ``flood`` — sync-backfill flood over COLD/CONFLICTING keys:
      every wave touches fresh rows and a mid-stream local write
      invalidates the whole cache.  The model where the cache cannot
      help; recorded (with its near-zero hit rate) so a hit-rate
      regression in the steady arm can't hide behind averaging.

    Device walls INCLUDE the final ``flush_barrier()`` — the win must
    survive paying for durability, not defer it.  All three arms
    (per-change oracle, plain batched, device) apply byte-identical
    streams and must leave byte-identical CRDT state (in-bench
    state-digest parity); divergence voids the point."""
    import tempfile

    from corrosion_tpu.agent.metrics import Metrics
    from corrosion_tpu.agent.storage import CrConn

    site = b"\x42" * 16

    def _mk_db(d, tag, device):
        db = CrConn(os.path.join(d, f"dev-{tag}.db"))
        db.conn.execute(
            "CREATE TABLE IF NOT EXISTS bench ("
            " id INTEGER PRIMARY KEY NOT NULL, a, b, c, d)"
        )
        db.as_crr("bench")
        db.metrics = Metrics()
        if device:
            db.enable_device_cache()
        return db

    def _scenario_waves(scenario):
        if scenario == "steady":
            # superseding col_versions over one fixed row set
            return [
                _apply_bench_changes(n_changes, site, col_version=2 + w)
                for w in range(waves)
            ]
        # flood: fresh rows every wave
        return [
            _apply_bench_changes(
                n_changes, site, col_version=1,
                row_offset=w * max(1, n_changes // 4),
            )
            for w in range(waves)
        ]

    def _run_arm(d, scenario, mode, wave_changes):
        db = _mk_db(d, f"{scenario}-{mode}", device=(mode == "device"))
        try:
            if scenario == "steady":
                db.apply_changes_batched(
                    _apply_bench_changes(n_changes, site, col_version=1)
                )
                # prefill flush excluded from the timed window: the
                # measurement starts with a warm cache and no backlog
                db.flush_barrier()
            t0 = time.perf_counter()
            for w, wc in enumerate(wave_changes):
                if scenario == "flood" and w == waves // 2:
                    # mid-stream local write: the invalidation event
                    # every arm replays identically (digest parity)
                    db.execute(
                        "INSERT OR REPLACE INTO bench (id, a) "
                        "VALUES (?, ?)", (-1, "local"),
                    )
                if mode == "per_change":
                    with db.apply_tx():
                        db.apply_changes_sequential_in_tx(list(wc))
                else:
                    db.apply_changes_batched(list(wc))
            db.flush_barrier()
            wall = time.perf_counter() - t0
            total = sum(len(wc) for wc in wave_changes)
            out = {
                "wall_s": round(wall, 4),
                "changes_per_s": round(total / max(wall, 1e-9), 1),
            }
            cache = None
            if mode == "device":
                m = db.metrics
                hits = m.get_counter_sum("corro_apply_cache_hits_total")
                misses = m.get_counter_sum(
                    "corro_apply_cache_misses_total")
                cache = {
                    "corro_apply_cache_hits_total": hits,
                    "corro_apply_cache_misses_total": misses,
                    "corro_apply_cache_evictions_total":
                        m.get_counter_sum(
                            "corro_apply_cache_evictions_total"),
                    "corro_apply_cache_invalidations_total":
                        m.get_counter_sum(
                            "corro_apply_cache_invalidations_total"),
                    "hit_rate": round(
                        hits / max(hits + misses, 1e-9), 4),
                }
            return out, _apply_state_digest(db), cache
        finally:
            db.close()

    scenarios = {}
    with tempfile.TemporaryDirectory(prefix="corro-apply-dev-") as d:
        # one unrecorded device warmup: cache/table allocation and the
        # ops import must not land inside the first timed scenario
        _run_arm(d, "warm", "device",
                 [_apply_bench_changes(512, site, col_version=2)])
        for scenario in ("steady", "flood"):
            wave_changes = _scenario_waves(scenario)
            row = {
                "waves": waves,
                "n_changes_per_wave": n_changes,
                "total_changes": sum(len(w) for w in wave_changes),
            }
            digests = {}
            for mode in ("per_change", "batched", "device"):
                out, dig, cache = _run_arm(d, scenario, mode,
                                           wave_changes)
                row[mode] = out
                digests[mode] = dig
                if cache is not None:
                    row["cache"] = cache
            row["parity"] = (
                digests["per_change"] == digests["batched"]
                == digests["device"]
            )
            row["speedup"] = round(
                row["device"]["changes_per_s"]
                / max(row["per_change"]["changes_per_s"], 1e-9), 2
            )
            row["speedup_batched"] = round(
                row["batched"]["changes_per_s"]
                / max(row["per_change"]["changes_per_s"], 1e-9), 2
            )
            scenarios[scenario] = row

    steady = scenarios["steady"]
    parity = all(s["parity"] for s in scenarios.values())
    floor = committed_floor
    return {
        "method": (
            f"{waves} waves of the headline change count per arm "
            "(pre-generated outside the timed window), one batched "
            "apply (or one per-change transaction) per wave; "
            "steady = superseding col_versions over one hot row set "
            "(prefilled, warm cache), flood = fresh rows every wave "
            "plus a mid-stream local write (whole-cache invalidation); "
            "device walls include the final flush_barrier; state "
            "digests asserted equal across per-change, batched and "
            "device arms per scenario"
        ),
        "n_changes": n_changes,
        "scenarios": scenarios,
        "parity": parity,
        "floor": floor,
        "pass": bool(
            parity
            and (floor is None or steady["speedup"] > floor)
            and steady["cache"]["hit_rate"] > 0.5
        ),
    }


def run_apply_bench(sizes=(1000, 10000), out_path="APPLY_BENCH.json"):
    """Per-change vs batched CRDT apply throughput (changes/s), cold
    (fresh rows) and warm (existing rows, superseding col_versions).
    Each measurement gets its own database; the paths are cross-checked
    to impact the same number of rows AND to leave byte-identical CRDT
    state (in-bench parity)."""
    import tempfile

    from corrosion_tpu.agent.metrics import Metrics
    from corrosion_tpu.agent.storage import CrConn

    site = b"\x42" * 16
    points = []

    def _mk_db(d, name):
        conn = CrConn(os.path.join(d, f"{name}.db"))
        conn.conn.execute(
            "CREATE TABLE IF NOT EXISTS bench ("
            " id INTEGER PRIMARY KEY NOT NULL, a, b, c, d)"
        )
        conn.as_crr("bench")
        return conn

    def _measure(db, changes, batched):
        t0 = time.perf_counter()
        if batched:
            impacted = db.apply_changes_batched(changes)
        else:
            with db.apply_tx():
                impacted = db.apply_changes_sequential_in_tx(changes)
        return time.perf_counter() - t0, impacted

    with tempfile.TemporaryDirectory(prefix="corro-apply-bench-") as d:
        # one unrecorded warmup apply per path: first-use costs (the
        # ops/numpy import in the columnar kernel, allocator growth)
        # must not land inside the first timed point
        wdb = _mk_db(d, "warmup")
        try:
            wchanges = _apply_bench_changes(512, site, col_version=1)
            wdb.apply_changes_batched(wchanges)
            with wdb.apply_tx():
                wdb.apply_changes_sequential_in_tx(
                    _apply_bench_changes(64, site, col_version=2)
                )
        finally:
            wdb.close()
        for n in sizes:
            cold = _apply_bench_changes(n, site, col_version=1)
            warm = _apply_bench_changes(n, site, col_version=2)
            for mode in ("cold", "warm"):
                row = {"n_changes": n, "mode": mode}
                impacts = {}
                digests = {}
                for batched in (False, True):
                    key = "batched" if batched else "per_change"
                    db = _mk_db(d, f"{n}-{mode}-{key}")
                    try:
                        if mode == "warm":
                            # pre-populate rows, then time the
                            # superseding second pass
                            db.apply_changes_batched(cold)
                        if batched:
                            # record which merge kernel the production
                            # dispatch selects at this batch size
                            # (fresh sink: exclude any warm prefill)
                            db.metrics = Metrics()
                        wall, impacted = _measure(
                            db, warm if mode == "warm" else cold, batched
                        )
                        if batched:
                            kernels = sorted({
                                dict(k).get("kernel") for k in
                                db.metrics.histogram_samples(
                                    "corro_apply_merge_seconds")
                            })
                            row["kernel"] = (
                                kernels[0] if len(kernels) == 1
                                else kernels
                            )
                        digests[key] = _apply_state_digest(db)
                    finally:
                        db.close()
                    impacts[key] = impacted
                    row[key] = {
                        "wall_s": round(wall, 4),
                        "changes_per_s": round(n / max(wall, 1e-9), 1),
                        "rows_impacted": impacted,
                    }
                if impacts["per_change"] != impacts["batched"]:
                    row["error"] = (
                        "impact mismatch: per_change="
                        f"{impacts['per_change']} "
                        f"batched={impacts['batched']}"
                    )
                row["parity"] = (
                    digests["per_change"] == digests["batched"]
                )
                if not row["parity"]:
                    row["error"] = (
                        "state divergence: per-change and batched "
                        "applies left different CRDT state"
                    )
                row["speedup"] = round(
                    row["batched"]["changes_per_s"]
                    / max(row["per_change"]["changes_per_s"], 1e-9), 2
                )
                points.append(row)
    headline = next(
        (p for p in points
         if p["n_changes"] == max(sizes) and p["mode"] == "cold"),
        points[-1],
    )
    committed = _committed_json(out_path) if out_path else None
    bad = [p for p in points if "error" in p]
    out = {
        "metric": "apply_batched_speedup",
        # a speedup over DIVERGENT semantics must not read as a clean
        # headline: any impact mismatch voids the value
        "value": None if bad else headline["speedup"],
        "unit": "x",
        "conditions": (
            "changes/s applying one remote actor's cell changes "
            "(n/4 rows x 4 cells) through apply_changes_sequential_in_tx "
            "vs apply_changes_batched, one transaction each; cold = "
            "fresh rows, warm = superseding col_versions over "
            "existing rows"
        ),
        "points": points,
    }
    if bad:
        out["error"] = (
            f"{len(bad)} point(s) with per-change/batched "
            "rows-impacted mismatch"
        )
    # observability overhead gate: paired in-run A/B at the ingest
    # layer (where the plane actually runs — the storage-level numbers
    # above never execute it); committed headline recorded as
    # cross-run context only
    committed_hl = None
    if committed:
        committed_hl = next(
            (p["batched"]["changes_per_s"]
             for p in committed.get("points", ())
             if p.get("n_changes") == headline["n_changes"]
             and p.get("mode") == "cold" and "batched" in p),
            None,
        )
    if headline["n_changes"] >= 5000:
        # columnar-kernel off/on paired A/B + state parity at the
        # headline shape (docs/crdts.md "Columnar merge kernel")
        out["kernel_ab"] = _apply_kernel_ab(headline["n_changes"])
        if out["kernel_ab"]["pass"] is False:
            out.setdefault(
                "error",
                "columnar kernel A/B failed: kernel-on apply "
                "regressed > 10% vs the dict oracle (or diverged) in "
                "paired A/B",
            )
        # event-loop stall gate: batched applies ride executor
        # threads; the loop must stay schedulable throughout
        out["stall_gate"] = _apply_stall_gate(headline["n_changes"])
        if out["stall_gate"]["pass"] is False:
            out.setdefault(
                "error",
                "apply stall gate failed: event-loop max stall over "
                "the 50 ms budget during batched applies",
            )
        # device-resident apply arm (docs/crdts.md "Device-resident
        # apply"): hot-cache steady-state must beat the committed
        # columnar cold headline, with digest parity across arms
        out["device_arm"] = _apply_device_arm(
            headline["n_changes"],
            committed_floor=committed.get("value") if committed
            else None,
        )
        if out["device_arm"]["pass"] is False:
            out.setdefault(
                "error",
                "device-resident arm failed: steady-state hot-cache "
                "speedup under the committed columnar headline floor, "
                "hit rate under 0.5, or state-digest divergence",
            )
        out["overhead_gate"] = _apply_overhead_ab(
            headline["n_changes"],
            committed=committed_hl,
            measured=headline["batched"]["changes_per_s"],
        )
        if out["overhead_gate"]["pass"] is False:
            out.setdefault(
                "error",
                "observability overhead gate failed: plane-on ingest "
                "throughput regressed > 5% vs plane-off in paired A/B",
            )
        # signed-attribution overhead gate (docs/faults.md): the same
        # paired-A/B discipline applied to the signing knob at the
        # APPLY ingest layer
        out["sig_overhead_gate"] = _sig_overhead_ab(
            headline["n_changes"]
        )
        if out["sig_overhead_gate"]["pass"] is False:
            out.setdefault(
                "error",
                "signed-attribution overhead gate failed: signing-on "
                "ingest throughput regressed > 5% vs signing-off in "
                "paired A/B",
            )
    else:
        out["overhead_gate"] = {
            "pass": None,
            "skipped": "smoke scale (n_changes < 5000): plane cost "
                       "below noise floor; gated at the 10k headline",
        }
        out["sig_overhead_gate"] = dict(out["overhead_gate"])
        out["kernel_ab"] = dict(out["overhead_gate"])
        out["stall_gate"] = dict(out["overhead_gate"])
        out["device_arm"] = dict(out["overhead_gate"])
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


# -- sync serve-path microbenchmark (bench.py --sync) ------------------


def _sync_seed_server(db_dir: str, n_versions: int) -> bytes:
    """Seed a server database with ``n_versions`` complete versions from
    one foreign origin actor (2 cells/version over distinct rows — the
    cold-backfill shape a restarted peer requests), via the merged
    apply-transaction path so seeding stays fast.  Returns the origin
    actor id."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ActorId, Version
    from corrosion_tpu.types.base import CrsqlDbVersion, CrsqlSeq
    from corrosion_tpu.types.change import Change
    from corrosion_tpu.types.changeset import Changeset, ChangeV1

    origin = b"\x51" * 16
    a = make_offline_agent(tmpdir=db_dir)
    try:
        ts = a.clock.new_timestamp()
        cvs = []
        for v in range(1, n_versions + 1):
            changes = [
                Change(
                    table="tests", pk=pack_values([v * 4 + i]),
                    cid="text", val=f"v{v}-{i}", col_version=1,
                    db_version=CrsqlDbVersion(v), seq=CrsqlSeq(i),
                    site_id=origin, cl=1,
                )
                for i in range(2)
            ]
            cvs.append(ChangeV1(
                actor_id=ActorId(origin),
                changeset=Changeset.full(Version(v), changes, (0, 1), 1,
                                         ts),
            ))
        for i in range(0, len(cvs), 500):
            a._apply_complete_group(origin, cvs[i : i + 500])
    finally:
        a.storage.close()
    return origin


async def _stall_probe(stats: dict, interval: float = 0.005):
    """Record the worst event-loop scheduling gap while serving."""
    import asyncio as _asyncio

    loop = _asyncio.get_running_loop()
    last = loop.time()
    while True:
        await _asyncio.sleep(interval)
        now = loop.time()
        stats["max_stall_ms"] = max(
            stats.get("max_stall_ms", 0.0), (now - last - interval) * 1e3
        )
        last = now


def _sync_serve_once(agent, origin: bytes, n_versions: int,
                     batched: bool) -> dict:
    """One full-range serve of the backfill need into a capture writer,
    with a concurrent stall probe; returns wall/bytes/stall."""
    import asyncio as _asyncio

    from corrosion_tpu.agent.testing import CaptureWriter
    from corrosion_tpu.types import SyncNeedV1

    async def run():
        agent.config.sync_batched_serve = batched
        stats = {"max_stall_ms": 0.0}
        probe = _asyncio.ensure_future(_stall_probe(stats))
        w = CaptureWriter()
        t0 = time.perf_counter()
        try:
            await agent._serve_need(
                w, origin, SyncNeedV1.full(1, n_versions)
            )
        finally:
            wall = time.perf_counter() - t0
            probe.cancel()
        return {"wall_s": wall, "bytes": bytes(w.buf),
                "max_stall_ms": stats["max_stall_ms"]}

    return _asyncio.run(run())


async def _sync_live_backfill(seed_dir: str, n_versions: int,
                              origin: bytes, timeout: float = 180.0) -> dict:
    """The end-to-end shape: a fresh node bootstraps to the seeded
    server and backfills every version through real sync sessions,
    with the shared event loop under a stall probe."""
    import asyncio as _asyncio
    import tempfile

    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA, wait_for

    server = Agent(AgentConfig(db_path=os.path.join(
        seed_dir, "corrosion.db")))
    await server.start()
    client_dir = tempfile.mkdtemp(prefix="corro-sync-client-")
    client = Agent(AgentConfig(
        db_path=os.path.join(client_dir, "corrosion.db"),
        bootstrap=[f"127.0.0.1:{server.gossip_addr[1]}"],
        schema_sql=TEST_SCHEMA,
        sync_interval_min=0.1, sync_interval_max=0.3,
    ))
    stats = {"max_stall_ms": 0.0}
    probe = None
    t0 = time.perf_counter()
    converged = True
    try:
        await client.start()
        # probe armed once both agents are live: the stall series must
        # measure the backfill, not schema apply / socket setup
        probe = _asyncio.ensure_future(_stall_probe(stats))
        bv = client.bookie.for_actor(origin)
        try:
            await wait_for(
                lambda: bv.last() >= n_versions
                and bv.contains_range(1, n_versions),
                timeout=timeout, interval=0.1,
            )
        except TimeoutError:
            converged = False
        wall = time.perf_counter() - t0
    finally:
        if probe is not None:
            probe.cancel()
        await client.stop()
        await server.stop()
    return {
        "wall_s": round(wall, 3),
        "changes_per_s": round(2 * n_versions / max(wall, 1e-9), 1),
        # BOTH agents share this loop (plus the client's on-loop sync
        # decode), so this measures harness loop saturation, not the
        # serve path — the serve-side stall gate is the direct-serve
        # max_stall_ms above
        "shared_loop_max_stall_ms": round(stats["max_stall_ms"], 2),
        "converged": converged,
    }


def run_sync_bench(n_versions: int = 10_000,
                   out_path: str = "SYNC_BENCH.json",
                   live: bool = True) -> dict:
    """Serve-path throughput: a restarted peer's full-range backfill
    need served per-version (the parity oracle) vs batched (range
    bookkeeping resolution + off-loop RO-pool collection + coalesced
    framing), cold (fresh connections/page cache) and warm (second
    serve; bookkeeping/snapshot caches hot), with served-bytes parity
    asserted — a mismatch voids the headline — plus the event-loop max
    stall while serving and (``live``) a real two-node backfill."""
    import tempfile

    from corrosion_tpu.agent.runtime import Agent, AgentConfig

    n_changes = 2 * n_versions
    points: dict = {}
    blobs: dict = {}
    with tempfile.TemporaryDirectory(prefix="corro-sync-bench-") as d:
        origin = _sync_seed_server(d, n_versions)
        for batched in (False, True):
            key = "batched" if batched else "per_version"
            # a fresh agent per mode: cold sqlite page cache + RO pool
            agent = Agent(AgentConfig(db_path=os.path.join(
                d, "corrosion.db")))
            try:
                mode: dict = {}
                for phase in ("cold", "warm"):
                    r = _sync_serve_once(agent, origin, n_versions,
                                         batched)
                    mode[phase] = {
                        "wall_s": round(r["wall_s"], 4),
                        "changes_per_s": round(
                            n_changes / max(r["wall_s"], 1e-9), 1),
                        "served_bytes": len(r["bytes"]),
                        "max_stall_ms": round(r["max_stall_ms"], 2),
                    }
                    if phase == "cold":
                        blobs[key] = r["bytes"]
                points[key] = mode
            finally:
                if agent._serve_pool is not None:
                    agent._serve_pool.shutdown(wait=True)
                agent.storage.close()
        live_stats = None
        if live:
            live_stats = asyncio.run(
                _sync_live_backfill(d, n_versions, origin)
            )
    parity_ok = blobs["per_version"] == blobs["batched"]
    speedup = round(
        points["batched"]["cold"]["changes_per_s"]
        / max(points["per_version"]["cold"]["changes_per_s"], 1e-9), 2
    )
    out = {
        "metric": "sync_serve_batched_speedup",
        # a speedup over DIVERGENT wire bytes must not read as a clean
        # headline: any served-bytes mismatch voids the value
        "value": speedup if parity_ok else None,
        "unit": "x",
        "conditions": (
            "changes/s serving one foreign actor's full-range backfill "
            f"need ({n_versions} versions x 2 cells) through _serve_need, "
            "per-version oracle vs batched pipeline, cold = fresh "
            "connections, warm = second serve; served bytes compared "
            "for equality; event-loop max stall sampled at 5 ms while "
            "serving"
        ),
        "n_versions": n_versions,
        "n_changes": n_changes,
        "parity_ok": parity_ok,
        "points": points,
    }
    if not parity_ok:
        out["error"] = "served-bytes mismatch between oracle and batched"
    if live_stats is not None:
        out["live_backfill"] = live_stats
        if not live_stats["converged"]:
            out.setdefault(
                "error", "live two-node backfill did not converge"
            )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


# -- bootstrap recovery benchmark (bench.py --boot) --------------------


async def _boot_arm(seed_dir: str, origin: bytes, n_versions: int,
                    snapshot: bool, timeout: float = 300.0) -> dict:
    """One bootstrap arm: a server over the seeded history and a FRESH
    node recovering from zero.  ``snapshot=False`` is the oracle arm —
    floors never advance (snapshot_serve off, retain disabled), every
    version crosses change-by-change; ``snapshot=True`` compacts the
    server's floor over the whole history first, so the client's only
    below-floor path is snapshot install + tail sync.  Recovery wall
    runs from client construction to full containment, and the
    client's own flight recorder journals the trajectory."""
    import tempfile as _tempfile

    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA, wait_for

    server = Agent(AgentConfig(
        db_path=os.path.join(seed_dir, "corrosion.db"),
        snapshot_serve=snapshot,
        snapshot_retain_versions=0 if snapshot else -1,
    ))
    await server.start()
    if snapshot:
        # maintenance-driven history compaction, run eagerly: the
        # origin's whole ledger drops below the snapshot floor
        await asyncio.get_running_loop().run_in_executor(
            None, server._compaction_pass
        )
        floor = server.bookie.for_actor(origin).snap_floor
        assert floor >= n_versions, floor
    import shutil as _shutil

    client_dir = _tempfile.mkdtemp(prefix="corro-boot-client-")
    client = Agent(AgentConfig(
        db_path=os.path.join(client_dir, "corrosion.db"),
        bootstrap=[f"127.0.0.1:{server.gossip_addr[1]}"],
        schema_sql=TEST_SCHEMA,
        sync_interval_min=0.1, sync_interval_max=0.3,
        snapshot_install=snapshot,
        flight_interval_s=0.25,
    ))
    t0 = time.perf_counter()
    converged = True
    try:
        await client.start()

        def _contained() -> bool:
            # re-fetch per check: a snapshot install REBUILDS the
            # bookie's per-actor ledgers in place, so a captured
            # BookedVersions reference would go stale at the swap
            bv = client.bookie.for_actor(origin)
            return (bv.last() >= n_versions
                    and bv.contains_range(1, n_versions))

        try:
            await wait_for(_contained, timeout=timeout, interval=0.05)
        except TimeoutError:
            converged = False
        wall = time.perf_counter() - t0
        installs = client.metrics.get_counter(
            "corro_snapshot_installs_total", result="ok"
        )
        # the flight-recorder trajectory: the client's own journal of
        # the recovery, offsets relative to the measured t0 — the
        # artifact gate reads the install event out of THIS record
        wall0 = time.time() - (time.perf_counter() - t0)
        events = []
        if client.flight is not None:
            for e in client.flight.entries(kind="event"):
                if e["kind"].startswith(("snap_", "sync_client")):
                    events.append({
                        "kind": e["kind"],
                        "t_s": round(e["wall"] - wall0, 3),
                        "attrs": e.get("attrs", {}),
                    })
        served_bytes = server.metrics.get_counter(
            "corro_snapshot_bytes_total", dir="served"
        )
    finally:
        await client.stop()
        await server.stop()
        _shutil.rmtree(client_dir, ignore_errors=True)
    return {
        "mode": "snapshot" if snapshot else "changes",
        "recovery_s": round(wall, 3),
        "converged": converged,
        "versions_per_s": round(n_versions / max(wall, 1e-9), 1),
        "snapshot_installs": installs,
        "snapshot_served_bytes": served_bytes,
        "trajectory": events[:50],
    }


def run_boot_bench(n_versions: int = 10_000,
                   out_path: str = "BOOT_BENCH.json") -> dict:
    """Recovery-time benchmark (docs/sync.md, docs/ops.md): a fresh
    node bootstrapping a ``n_versions`` foreign history change-by-
    change (the pre-snapshot oracle) vs via snapshot install + tail
    sync.  Headline: the snapshot path's recovery speedup, gated >=5x
    at the 10k shape with the recovery-time budget in-record; the
    trajectory (the client's own flight-recorder journal) must show
    the install completing the recovery."""
    import tempfile

    points: dict = {}
    with tempfile.TemporaryDirectory(prefix="corro-boot-bench-") as d:
        origin = _sync_seed_server(d, n_versions)
        # oracle arm FIRST: it needs the uncompacted ledger
        points["changes"] = asyncio.run(
            _boot_arm(d, origin, n_versions, snapshot=False)
        )
        points["snapshot"] = asyncio.run(
            _boot_arm(d, origin, n_versions, snapshot=True)
        )
    ch, sn = points["changes"], points["snapshot"]
    speedup = round(
        ch["recovery_s"] / max(sn["recovery_s"], 1e-9), 2
    )
    ok = ch["converged"] and sn["converged"] \
        and sn["snapshot_installs"] >= 1
    install_events = [
        e for e in sn["trajectory"] if e["kind"] == "snap_install"
    ]
    # the budget the artifact lint asserts in-record: the snapshot
    # recovery must beat HALF the oracle's wall outright (the >=5x
    # headline floor is separately asserted at the 10k shape)
    budget_s = round(max(5.0, ch["recovery_s"] / 2.0), 3)
    out = {
        "metric": "boot_recovery_speedup",
        "value": speedup if ok else None,
        "unit": "x",
        "conditions": (
            f"fresh-node recovery of a {n_versions}-version foreign "
            "history (2 cells/version): change-by-change anti-entropy "
            "(uncompacted server, snapshot off) vs snapshot install + "
            "tail sync (server floor compacted over the whole "
            "history); wall from client construction to full "
            "containment of versions 1..n, one live server per arm on "
            "loopback"
        ),
        "n_versions": n_versions,
        "recovery_budget_s": budget_s,
        "points": points,
        "gates": {
            "both_converged": ch["converged"] and sn["converged"],
            "installed_via_snapshot": sn["snapshot_installs"] >= 1,
            "trajectory_has_install": len(install_events) >= 1,
            "within_budget": sn["recovery_s"] <= budget_s,
        },
    }
    if not ok:
        out["error"] = "bootstrap arm failed to converge or install"
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


# -- write-path microbenchmark (bench.py --write) ----------------------


def _write_bench_once(d: str, n_tx: int, writers: int, combined: bool,
                      cfg_overrides: dict | None = None,
                      tag: str = "", setup=None,
                      drain_subs: bool = False):
    """One mode point: a live (started) agent with no peers, ``writers``
    threads splitting ``n_tx`` single-upsert transactions over disjoint
    rows, the shared event loop under a 5 ms stall probe.  Returns the
    timing row and a converged-state snapshot for the parity check.
    ``cfg_overrides`` may override ANY config default (including
    ``subs_enabled``); ``setup(agent)`` runs after start, before the
    probe is armed — the subs-plane A/B registers its standing
    subscriptions there.  ``drain_subs`` extends the measured wall
    until the subscription matcher has fully drained: commit AND
    deliver, so arms that defer matcher work cannot bank it outside
    the clock."""
    import asyncio as _asyncio
    from concurrent.futures import ThreadPoolExecutor

    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA

    key = "combined" if combined else "per_tx"
    base = dict(
        db_path=os.path.join(d, f"write-{n_tx}-{writers}-{key}{tag}.db"),
        schema_sql=TEST_SCHEMA,
        api_port=None,
        subs_enabled=False,
        write_group_commit=combined,
    )
    base.update(cfg_overrides or {})
    cfg = AgentConfig(**base)
    per = max(1, n_tx // writers)

    async def run():
        import threading

        agent = Agent(cfg)
        await agent.start()
        if setup is not None:
            setup(agent)
        loop = _asyncio.get_running_loop()

        def writer(w: int):
            lats = []
            base = w * per
            for i in range(per):
                t0 = time.perf_counter()
                agent.execute_transaction([(
                    "INSERT INTO tests (id, text) VALUES (?, ?) "
                    "ON CONFLICT(id) DO UPDATE SET text=excluded.text",
                    (base + i, f"w{w}-{i}"),
                )])
                lats.append(time.perf_counter() - t0)
            return lats

        pool = ThreadPoolExecutor(max_workers=writers,
                                  thread_name_prefix="bench-writer")
        # pre-warm every writer thread BEFORE arming the probe: the
        # stall series must measure the write path, not thread spin-up
        # (the sync bench arms its probe after agent setup the same way)
        bar = threading.Barrier(writers + 1)
        warm = [
            loop.run_in_executor(pool, bar.wait) for _ in range(writers)
        ]
        await loop.run_in_executor(None, bar.wait)
        await _asyncio.gather(*warm)
        stats = {"max_stall_ms": 0.0}
        probe = _asyncio.ensure_future(_stall_probe(stats))
        t0 = time.perf_counter()
        try:
            lats = await _asyncio.gather(*[
                loop.run_in_executor(pool, writer, w)
                for w in range(writers)
            ])
            if drain_subs and agent.subs is not None:
                from corrosion_tpu.agent.testing import wait_for

                await wait_for(
                    lambda: agent.subs.idle(), timeout=300.0
                )
            wall = time.perf_counter() - t0
        finally:
            probe.cancel()
            pool.shutdown(wait=True)
        # converged-state snapshot BEFORE stop: final table data plus
        # gapless version accounting — the cross-mode parity operands
        _, rows = agent.storage.read_query(
            "SELECT id, text FROM tests ORDER BY id"
        )
        bv = agent.bookie.for_actor(agent.actor_id)
        snap = {
            "rows": [tuple(r) for r in rows],
            "n_versions": bv.last(),
            "gapless": bv.contains_range(1, bv.last()),
        }
        groups = agent.metrics.get_counter("corro_write_groups_total")
        await agent.stop()
        flat = sorted(x for sub in lats for x in sub)
        total = writers * per
        return {
            "n_committed": total,
            "wall_s": round(wall, 4),
            "tx_per_s": round(total / max(wall, 1e-9), 1),
            "p50_ms": round(flat[len(flat) // 2] * 1e3, 3),
            "p99_ms": round(
                flat[min(len(flat) - 1, int(len(flat) * 0.99))] * 1e3, 3
            ),
            "max_stall_ms": round(stats["max_stall_ms"], 2),
            "mean_group_size": (
                round(total / groups, 2) if groups else None
            ),
        }, snap

    return _asyncio.run(run())


def _write_stall_idle_baseline(seconds: float) -> float:
    """Max event-loop stall of an IDLE started agent over ``seconds`` —
    the host's scheduler noise floor, printed next to the gate so a
    shared/small machine's jitter is legible in the artifact."""
    import asyncio as _asyncio
    import tempfile

    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA

    async def run():
        d = tempfile.mkdtemp(prefix="corro-write-idle-")
        agent = Agent(AgentConfig(
            db_path=os.path.join(d, "idle.db"), schema_sql=TEST_SCHEMA,
            api_port=None, subs_enabled=False,
        ))
        await agent.start()
        stats = {"max_stall_ms": 0.0}
        probe = _asyncio.ensure_future(_stall_probe(stats))
        await _asyncio.sleep(seconds)
        probe.cancel()
        await agent.stop()
        return stats["max_stall_ms"]

    return _asyncio.run(run())


# the convergence observability plane's knobs, all off — the A/B
# baseline arm (defaults leave them all on)
_PLANE_OFF = {
    "provenance": False,
    "bcast_trace_propagation": False,
    "stall_probe_interval": 0.0,
}


def _write_overhead_ab(n_tx: int, writers: int,
                       committed=None, measured=None, reps: int = 3,
                       max_regression: float = 0.05,
                       off_overrides: dict | None = None,
                       plane_desc: str | None = None) -> dict:
    """Paired A/B of the observability plane's write-path cost at one
    shape: ``reps`` temporally-adjacent (plane-off, plane-on) pairs of
    combined-mode runs, arm order alternating per pair so warm-up and
    disk-state effects cancel.  The gate is the MEDIAN of the per-pair
    on/off ratios — host noise on a shared box swings single runs
    >10%, but it drifts slowly, so a within-pair ratio is stable where
    a cross-pair (or cross-run) comparison is not."""
    import statistics
    import tempfile

    if off_overrides is None:
        off_overrides = _PLANE_OFF
        plane_desc = (
            "plane = provenance + broadcast trace propagation "
            "+ stall probe"
        )
    pairs = []
    with tempfile.TemporaryDirectory(prefix="corro-write-ab-") as d:
        for rep in range(reps):
            arms = (("off", off_overrides), ("on", None))
            if rep % 2:
                arms = arms[::-1]
            tx = {}
            for arm, over in arms:
                r, _snap = _write_bench_once(
                    d, n_tx, writers, combined=True,
                    cfg_overrides=over, tag=f"-ab-{arm}{rep}",
                )
                tx[arm] = r["tx_per_s"]
            pairs.append({
                "off_tx_per_s": tx["off"],
                "on_tx_per_s": tx["on"],
                "ratio": round(tx["on"] / max(tx["off"], 1e-9), 4),
            })
    ratio = statistics.median(p["ratio"] for p in pairs)
    gate = {
        "method": (
            f"paired in-run A/B, {reps} adjacent off/on pairs at the "
            "headline shape (arm order alternating), median per-pair "
            f"ratio; {plane_desc}"
        ),
        "n_tx": n_tx,
        "writers": writers,
        "pairs": pairs,
        "ratio": round(ratio, 4),
        "max_regression": max_regression,
        "pass": bool(ratio >= 1.0 - max_regression),
    }
    if committed is not None and measured is not None:
        # cross-run context only (host drift between sessions dwarfs
        # the plane's cost — see method note)
        gate["committed"] = committed
        gate["committed_ratio"] = round(measured / committed, 4)
    return gate


def run_write_bench(sizes=(1000, 10000), writers=(1, 8, 32),
                    out_path="WRITE_BENCH.json") -> dict:
    """Local write-path throughput: concurrent client transactions
    through the per-tx oracle vs the group-commit write combiner
    (docs/writes.md), with per-transaction p99 latency, event-loop max
    stall sampled at 5 ms during the run, and converged-state parity
    (final rows + gapless version accounting) asserted per point — a
    mismatch voids the headline."""
    import sys
    import tempfile

    def _points() -> list:
        pts = []
        with tempfile.TemporaryDirectory(prefix="corro-write-bench-") as d:
            for n_tx in sizes:
                for w in writers:
                    row = {"n_tx": n_tx, "writers": w}
                    snaps = {}
                    for combined in (False, True):
                        key = "combined" if combined else "per_tx"
                        r, snap = _write_bench_once(d, n_tx, w, combined)
                        row[key] = r
                        snaps[key] = snap
                    parity = (
                        snaps["per_tx"]["rows"] == snaps["combined"]["rows"]
                        and snaps["per_tx"]["n_versions"]
                        == snaps["combined"]["n_versions"]
                        and snaps["per_tx"]["gapless"]
                        and snaps["combined"]["gapless"]
                    )
                    row["parity_ok"] = parity
                    if not parity:
                        row["error"] = (
                            "converged-state mismatch between per-tx and "
                            "combined"
                        )
                    row["speedup"] = round(
                        row["combined"]["tx_per_s"]
                        / max(row["per_tx"]["tx_per_s"], 1e-9), 2
                    )
                    pts.append(row)
        return pts

    # many writer threads cede the GIL to the event loop in
    # switch-interval quanta: the default 5 ms quantum lets a 32-thread
    # herd hold the loop off for tens of ms between probe samples,
    # drowning the write path's own signal — tighten it for the run
    old_swi = sys.getswitchinterval()
    sys.setswitchinterval(0.002)
    try:
        points = _points()
        # dedicated stall gate (the --sync gate's shape: a short direct
        # measurement window): the combined path at the headline writer
        # count over a few-second burst.  The per-point max_stall_ms
        # columns above span 20-60 s windows — on a small/shared host
        # the OS scheduler alone produces >50 ms one-off gaps at that
        # exposure (see idle_max_stall_ms for this host's floor), so
        # the gate is this bounded window, not the sweep columns.
        with tempfile.TemporaryDirectory(
            prefix="corro-write-stall-"
        ) as d:
            gate_w = max(writers)
            gate_n = min(2000, max(sizes))
            # two bursts, gate on the min: a systematic on-loop stall
            # (SQL/encoding on the loop) reproduces in EVERY burst,
            # while a one-off scheduler glitch does not
            bursts = [
                _write_bench_once(
                    tempfile.mkdtemp(dir=d), gate_n, gate_w, True
                )[0]
                for _ in range(2)
            ]
        best = min(bursts, key=lambda r: r["max_stall_ms"])
        stall_gate = {
            "n_tx": gate_n,
            "writers": gate_w,
            "combined_max_stall_ms": best["max_stall_ms"],
            "burst_max_stall_ms": [r["max_stall_ms"] for r in bursts],
            "wall_s": best["wall_s"],
            "idle_max_stall_ms": round(
                _write_stall_idle_baseline(max(1.0, best["wall_s"])), 2
            ),
        }
    finally:
        sys.setswitchinterval(old_swi)
    headline = next(
        (p for p in points
         if p["n_tx"] == max(sizes) and p["writers"] == max(writers)),
        points[-1],
    )
    committed = _committed_json(out_path) if out_path else None
    bad = [p for p in points if "error" in p]
    out = {
        "metric": "write_group_commit_speedup",
        # a speedup over DIVERGENT converged state must not read as a
        # clean headline: any parity mismatch voids the value
        "value": None if bad else headline["speedup"],
        "unit": "x",
        "conditions": (
            "transactions/s over concurrent writer threads each running "
            "single-upsert transactions on disjoint rows through "
            "execute_transaction, per-tx oracle vs group-commit "
            "combiner, cold database per mode; converged rows + gapless "
            "versions compared for equality; per-tx p99 latency and "
            "event-loop max stall sampled at 5 ms during every run; "
            "stall_gate = a bounded combined-path burst at the headline "
            "writer count next to the same host's idle-loop noise floor"
        ),
        "headline": {
            "n_tx": headline["n_tx"], "writers": headline["writers"],
        },
        "stall_gate": stall_gate,
        "points": points,
    }
    if bad:
        out["error"] = (
            f"{len(bad)} point(s) with per-tx/combined converged-state "
            "mismatch"
        )
    # observability overhead gate: PAIRED in-run A/B at the headline
    # shape — the plane (provenance + broadcast trace propagation +
    # stall probe) toggled off/on in temporally-adjacent pairs (arm
    # order alternating per pair), gating on the MEDIAN per-pair ratio
    # so low-frequency host drift cancels.  A cross-run comparison
    # against a JSON committed hours earlier measures that drift, not
    # the instrumentation (identical configs swing >25% on a shared
    # box), so the committed headline ratio is recorded as context
    # only.
    committed_hl = None
    if committed:
        committed_hl = next(
            (p["combined"]["tx_per_s"]
             for p in committed.get("points", ())
             if p.get("n_tx") == headline["n_tx"]
             and p.get("writers") == headline["writers"]
             and "combined" in p),
            None,
        )
    if headline["n_tx"] >= 5000:
        old_swi2 = sys.getswitchinterval()
        sys.setswitchinterval(0.002)
        try:
            out["overhead_gate"] = _write_overhead_ab(
                headline["n_tx"], headline["writers"],
                committed=committed_hl,
                measured=headline["combined"]["tx_per_s"],
            )
        finally:
            sys.setswitchinterval(old_swi2)
        if out["overhead_gate"]["pass"] is False:
            out.setdefault(
                "error",
                "observability overhead gate failed: plane-on combined "
                "throughput regressed > 5% vs plane-off in paired A/B",
            )
    else:
        # sub-second arms at smoke shapes sit below the host's
        # run-to-run noise floor — the median pair ratio gates nothing
        # there, so the A/B runs only at the 10k headline (@slow tier
        # and artifact generation)
        out["overhead_gate"] = {
            "pass": None,
            "skipped": "smoke scale (n_tx < 5000): plane cost below "
                       "noise floor; gated at the 10k headline",
        }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


# -- subscription matcher plane (bench.py --subs) ----------------------

_SUBS_ACTOR = b"\xbb" * 16


def _subs_make_burst(rng, n_changes: int, pk_space: int,
                     wave_size: int) -> list:
    """The change burst as per-wave change lists: mostly upserts with a
    delete tail, several changes per pk so waves carry the duplicate
    and superseded work the columnar kernel exists to coalesce.
    Returns ``[(version, [Change, ...]), ...]``."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.types.change import (
        SENTINEL_CID,
        Change,
        CrsqlDbVersion,
        CrsqlSeq,
    )

    hi: dict = {}
    waves = []
    v = 0
    for base in range(0, n_changes, wave_size):
        v += 1
        changes = []
        for seq in range(min(wave_size, n_changes - base)):
            pk = rng.randrange(pk_space)
            if rng.random() < 0.1:
                changes.append(Change(
                    table="tests", pk=pack_values([pk]),
                    cid=SENTINEL_CID, val=None,
                    col_version=hi.get(pk, 1),
                    db_version=CrsqlDbVersion(v), seq=CrsqlSeq(seq),
                    site_id=_SUBS_ACTOR, cl=2,
                ))
            else:
                cv = hi.get(pk, 0) + 1
                hi[pk] = cv
                changes.append(Change(
                    table="tests", pk=pack_values([pk]), cid="text",
                    val=f"v{v}s{seq}", col_version=cv,
                    db_version=CrsqlDbVersion(v), seq=CrsqlSeq(seq),
                    site_id=_SUBS_ACTOR, cl=1,
                ))
        waves.append((v, changes))
    return waves


def _subs_make_population(rng, n_subs: int, pk_space: int,
                          broad_frac: float = 0.01) -> list:
    """Synthetic predicate population at production mix: ``broad_frac``
    whole-table subscriptions (every wave pk reaches each of them) and
    the rest pk IN-list predicates of 1-8 pks over the burst's pk
    space, half with a column-subset projection."""
    from corrosion_tpu.agent.pack import pack_values
    from corrosion_tpu.agent.submatch import SubSpec

    n_broad = max(1, int(n_subs * broad_frac))
    specs = []
    for i in range(n_subs):
        if i < n_broad:
            specs.append(SubSpec(f"s{i}", "tests", (0, 1)))
            continue
        pks = frozenset(
            pack_values([rng.randrange(pk_space)])
            for _ in range(rng.randint(1, 8))
        )
        proj = (0, 1) if rng.random() < 0.5 else (1,)
        specs.append(SubSpec(f"s{i}", "tests", proj, pks))
    return specs


def _subs_matcher_headline(n_subs: int, n_changes: int,
                           n_shards: int = 4,
                           subset_n: int | None = None,
                           seed: int = 7) -> dict:
    """The headline matcher A/B: the same converged database, the same
    change burst, the same predicate population — matched once through
    the sharded columnar pipeline (``submatch.resolve_wave`` +
    ``match_wave``, one row fetch per (shard, wave)) and once through
    the per-sub oracle discipline (one scoped SQL evaluation per
    (subscription, wave), measured over a proportional subset).
    Throughput is delivered (sub, pk) verdict pairs per second; the
    oracle arm is given a head start the real per-sub path does not
    get (wave pks pre-intersected with each IN-list predicate before
    its query), so the reported speedup is a floor.  In-bench parity:
    the two arms' final per-(sub, pk) verdicts over the subset must be
    identical — a mismatch voids the headline."""
    import random
    import shutil
    import tempfile
    import threading

    from corrosion_tpu.agent import submatch
    from corrosion_tpu.agent.pack import pack_values, unpack_values
    from corrosion_tpu.agent.runtime import ChangeSource
    from corrosion_tpu.agent.testing import make_offline_agent
    from corrosion_tpu.types import ActorId, Version
    from corrosion_tpu.types.changeset import Changeset, ChangeV1

    rng = random.Random(seed)
    pk_space = max(64, n_changes // 3)
    wave_size = min(512, max(64, n_changes // 8))
    waves = _subs_make_burst(rng, n_changes, pk_space, wave_size)
    specs = _subs_make_population(rng, n_subs, pk_space)
    if subset_n is None:
        subset_n = min(n_subs, 2000)
    subset = [specs[i]
              for i in sorted(rng.sample(range(n_subs), subset_n))]
    subset_ids = {s.sub_id for s in subset}

    d = tempfile.mkdtemp(prefix="corro-subs-bench-")
    agent = make_offline_agent(d, subs_enabled=False)
    try:
        # converge the database FIRST (the matcher runs post-apply,
        # exactly like on_change) — both arms then read the same truth
        ts = agent.clock.new_timestamp()
        for v, changes in waves:
            agent.handle_change(
                ChangeV1(
                    actor_id=ActorId(_SUBS_ACTOR),
                    changeset=Changeset.full(
                        Version(v), changes, (0, len(changes) - 1),
                        len(changes) - 1, ts,
                    ),
                ),
                ChangeSource.SYNC, rebroadcast=False,
            )

        def fetch(need):
            out = {}
            for i in range(0, len(need), 800):
                ints = [unpack_values(pk)[0] for pk in need[i:i + 800]]
                _, rows = agent.storage.read_query(
                    "SELECT id, text FROM tests WHERE id IN (%s)"
                    % ", ".join("?" * len(ints)),
                    ints,
                )
                for r in rows:
                    out[pack_values([r[0]])] = tuple(r)
            return out

        # -- columnar arm: one index + one worker thread per shard,
        # each resolving its own copy of every wave (what the manager's
        # _drain_waves does per shard)
        indexes = [submatch.ShardIndex() for _ in range(n_shards)]
        for spec in specs:
            indexes[submatch.shard_of(spec.sub_id, n_shards)].add(spec)
        col_state: list = [dict() for _ in range(n_shards)]
        col_pairs = [0] * n_shards

        def shard_worker(si: int):
            index, acc, n = indexes[si], col_state[si], 0
            for _v, changes in waves:
                if not index.has("tests"):
                    continue
                pks, _alive = submatch.resolve_wave(
                    changes, backend="numpy"
                )
                verdicts, n_pairs = submatch.match_wave(
                    index, "tests", pks, fetch
                )
                n += n_pairs
                for sid, per in verdicts.items():
                    if sid in subset_ids:
                        acc.setdefault(sid, {}).update(per)
            col_pairs[si] = n

        threads = [
            threading.Thread(target=shard_worker, args=(i,),
                             name=f"subs-bench-{i}")
            for i in range(n_shards)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        col_wall = time.perf_counter() - t0

        # -- oracle arm: per-(sub, wave) scoped SQL over the subset
        ora_state: dict = {}
        ora_pairs = 0
        t0 = time.perf_counter()
        for _v, changes in waves:
            seen: dict = {}
            for ch in changes:
                seen[ch.pk] = True
            wave_pks = list(seen)
            wave_ints = {pk: unpack_values(pk)[0] for pk in wave_pks}
            for spec in subset:
                if spec.pk_filter is None:
                    targeted = wave_pks
                else:
                    targeted = [pk for pk in wave_pks
                                if pk in spec.pk_filter]
                    if not targeted:
                        continue
                rows = {}
                for i in range(0, len(targeted), 800):
                    ints = [wave_ints[pk]
                            for pk in targeted[i:i + 800]]
                    _, got = agent.storage.read_query(
                        "SELECT id, text FROM tests WHERE id IN (%s)"
                        % ", ".join("?" * len(ints)),
                        ints,
                    )
                    for r in got:
                        rows[pack_values([r[0]])] = tuple(r)
                per = ora_state.setdefault(spec.sub_id, {})
                for pk in targeted:
                    per[pk] = rows.get(pk)
                ora_pairs += len(targeted)
        ora_wall = time.perf_counter() - t0
    finally:
        agent.storage.close()
        shutil.rmtree(d, ignore_errors=True)

    # -- in-bench parity over the subset: identical final verdicts
    compared = mismatches = 0
    for spec in subset:
        si = submatch.shard_of(spec.sub_id, n_shards)
        col = col_state[si].get(spec.sub_id, {})
        ora = ora_state.get(spec.sub_id, {})
        for pk in set(col) | set(ora):
            compared += 1
            if col.get(pk, "MISSING") != ora.get(pk, "MISSING"):
                mismatches += 1
    col_rate = sum(col_pairs) / max(col_wall, 1e-9)
    ora_rate = ora_pairs / max(ora_wall, 1e-9)
    return {
        "n_subs": n_subs,
        "n_changes": n_changes,
        "pk_space": pk_space,
        "wave_size": wave_size,
        "n_waves": len(waves),
        "columnar": {
            "n_shards": n_shards,
            "wall_s": round(col_wall, 4),
            "verdict_pairs": int(sum(col_pairs)),
            "pairs_per_s": round(col_rate, 1),
        },
        "oracle": {
            "subset_subs": subset_n,
            "wall_s": round(ora_wall, 4),
            "verdict_pairs": int(ora_pairs),
            "pairs_per_s": round(ora_rate, 1),
        },
        "speedup": round(col_rate / max(ora_rate, 1e-9), 2),
        "parity": {
            "subset_subs": subset_n,
            "compared_pairs": compared,
            "mismatches": mismatches,
            "ok": bool(mismatches == 0 and compared > 0),
        },
    }


def _subs_swarm(n_subs: int, n_writes: int, writers: int = 4,
                staleness_slo_s: float = 5.0,
                stall_budget_ms: float = 50.0) -> dict:
    """The production-shaped load point: a LIVE agent with ``n_subs``
    standing subscriptions across every served shape (broad columnar,
    projection, pk IN-list, COUNT(*)-only, bounded ORDER BY+LIMIT, and
    a WHERE the spec language rejects — the in-plane oracle fallback),
    ``writers`` threads bursting upserts+deletes through the write
    path, concurrent readers, and live subscribe churn — under a 5 ms
    event-loop stall probe and a 20 Hz staleness sampler.  Gates: max
    loop stall, p99 of every sampled ``corro_subs_staleness_seconds``
    series, and converged-state parity (every surviving subscription's
    materialized rows equal its query over the final database)."""
    import asyncio as _asyncio
    import random
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from corrosion_tpu.agent.runtime import Agent, AgentConfig
    from corrosion_tpu.agent.testing import TEST_SCHEMA, wait_for

    d = tempfile.mkdtemp(prefix="corro-subs-swarm-")
    rng = random.Random(11)
    per = max(1, n_writes // writers)

    sqls = [
        "SELECT * FROM tests",
        "SELECT text FROM tests",
        "SELECT count(*) FROM tests",
        "SELECT id, text FROM tests ORDER BY id LIMIT 10",
        "SELECT id, text FROM tests WHERE id % 3 = 0",
    ]
    seen_sqls = set(sqls)
    while len(sqls) < n_subs:
        pks = sorted(rng.sample(range(max(8, n_writes)),
                                min(6, max(2, n_writes // 4))))
        sql = ("SELECT id, text FROM tests WHERE id IN (%s)"
               % ", ".join(map(str, pks)))
        if sql not in seen_sqls:
            seen_sqls.add(sql)
            sqls.append(sql)

    async def run():
        agent = Agent(AgentConfig(
            db_path=os.path.join(d, "swarm.db"),
            schema_sql=TEST_SCHEMA,
            api_port=None,
            subs_enabled=True,
            flight_interval_s=0.25,
        ))
        await agent.start()
        loop = _asyncio.get_running_loop()
        handles = [agent.subs.subscribe(sql) for sql in sqls]
        # prime the staleness bases: registering the population takes
        # real time (one sqlite file + initial refresh per sub), and
        # last_ok_at starts at each sub's OWN subscribe — one write +
        # drain resets every base to now, so the sampled series
        # measures burst-time staleness, not setup skew
        agent.execute_transaction([(
            "INSERT INTO tests (id, text) VALUES (?, ?) "
            "ON CONFLICT(id) DO UPDATE SET text=excluded.text",
            (999_999_999, "prime"),
        )])
        await wait_for(lambda: agent.subs.idle(), timeout=60.0)

        stop = threading.Event()
        stale_samples: list = []
        depth_max = {"v": 0.0}
        churned = {"n": 0}

        def sampler():
            while not stop.is_set():
                for name, val, _lbl in agent.subs.metric_gauges():
                    if name == "corro_subs_staleness_seconds":
                        stale_samples.append(val)
                    elif name == "corro_subs_matcher_queue_depth":
                        depth_max["v"] = max(depth_max["v"], val)
                time.sleep(0.05)

        def reader():
            while not stop.is_set():
                agent.storage.read_query("SELECT count(*) FROM tests")
                time.sleep(0.002)

        def churner():
            # live subscribe churn: new predicates arriving while the
            # burst is in flight must register on their shard without
            # stalling the standing population
            i = 0
            while not stop.is_set():
                agent.subs.subscribe(
                    "SELECT id, text FROM tests WHERE id IN (%d, %d)"
                    % (10_000_000 + i, 10_000_001 + i)
                )
                churned["n"] += 1
                i += 1
                time.sleep(0.1)

        def writer(w: int):
            base = w * per
            for i in range(per):
                if i % 10 == 9 and i > 0:
                    agent.execute_transaction([(
                        "DELETE FROM tests WHERE id = ?",
                        (base + i - 1,),
                    )])
                else:
                    agent.execute_transaction([(
                        "INSERT INTO tests (id, text) VALUES (?, ?) "
                        "ON CONFLICT(id) DO UPDATE SET "
                        "text=excluded.text",
                        (base + i, f"w{w}-{i}"),
                    )])

        pool = ThreadPoolExecutor(max_workers=writers,
                                  thread_name_prefix="subs-swarm")
        bar = threading.Barrier(writers + 1)
        warm = [loop.run_in_executor(pool, bar.wait)
                for _ in range(writers)]
        await loop.run_in_executor(None, bar.wait)
        await _asyncio.gather(*warm)
        # aux load + samplers arm WITH the probe: the gated series
        # must cover the burst window, not agent setup
        aux = [threading.Thread(target=f, daemon=True)
               for f in (sampler, reader, reader, churner)]
        for t in aux:
            t.start()
        stats = {"max_stall_ms": 0.0}
        probe = _asyncio.ensure_future(_stall_probe(stats))
        t0 = time.perf_counter()
        try:
            await _asyncio.gather(*[
                loop.run_in_executor(pool, writer, w)
                for w in range(writers)
            ])
            # the matcher plane must drain the whole burst (idle()
            # raises if a shard worker died mid-run)
            await wait_for(lambda: agent.subs.idle(), timeout=120.0)
            wall = time.perf_counter() - t0
        finally:
            probe.cancel()
            stop.set()
            pool.shutdown(wait=True)
        for t in aux:
            t.join(timeout=2.0)

        # converged-state parity: each standing subscription's
        # materialized rows == its query over the final database
        mismatched = []
        for h in handles:
            with h._lock:
                got = sorted(
                    (tuple(c) for _rid, c in h.rows.values()), key=repr
                )
            _, rows = agent.storage.read_query(h.sql)
            want = sorted((tuple(r) for r in rows), key=repr)
            if got != want:
                mismatched.append(h.sql)

        counters = {
            name: float(agent.metrics.get_counter_sum(name))
            for name in (
                "corro_subs_columnar_rounds_total",
                "corro_subs_columnar_verdicts_total",
                "corro_subs_bounded_refresh_total",
                "corro_subs_delta_fallbacks_total",
                "corro_subs_events_dropped_total",
                "corro_subs_updates_dropped_total",
                "corro_subs_shard_overflow_total",
            )
        }
        stale = sorted(stale_samples)
        p99 = (stale[min(len(stale) - 1, int(len(stale) * 0.99))]
               if stale else 0.0)
        timeline = {"snapshots": 0, "event_counts": {}, "events": []}
        if agent.flight is not None:
            evs = agent.flight.entries(kind="event")
            kinds: dict = {}
            for e in evs:
                kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
            timeline = {
                "snapshots": agent.flight.snapshots,
                "event_counts": kinds,
                "events": [
                    {"kind": e["kind"], "attrs": e.get("attrs", {})}
                    for e in evs[:40]
                ],
            }
        await agent.stop()
        return {
            "n_subs": len(handles),
            "n_writes": writers * per,
            "writers": writers,
            "wall_s": round(wall, 3),
            "writes_per_s": round(writers * per / max(wall, 1e-9), 1),
            "churned_subs": churned["n"],
            "stall_gate": {
                "max_stall_ms": round(stats["max_stall_ms"], 2),
                "budget_ms": stall_budget_ms,
                "pass": bool(
                    stats["max_stall_ms"] <= stall_budget_ms
                ),
            },
            "staleness_gate": {
                "p99_s": round(p99, 3),
                "max_s": round(stale[-1], 3) if stale else 0.0,
                "slo_s": staleness_slo_s,
                "samples": len(stale),
                "pass": bool(p99 <= staleness_slo_s and stale),
            },
            "parity_ok": not mismatched,
            "mismatched_subs": mismatched[:5],
            "queue_depth_max": depth_max["v"],
            "counters": counters,
            "timeline": timeline,
        }

    import sys
    old_swi = sys.getswitchinterval()
    sys.setswitchinterval(0.002)
    try:
        return _asyncio.run(run())
    finally:
        sys.setswitchinterval(old_swi)
        shutil.rmtree(d, ignore_errors=True)


def _subs_overhead_ab(n_tx: int, writers: int, n_subs: int = 200,
                      reps: int = 3,
                      max_regression: float = 0.05) -> dict:
    """Paired off/on A/B of the SHARDED COLUMNAR matcher's write-path
    cost at the WRITE_BENCH headline shape: both arms carry the same
    ``n_subs`` standing subscriptions (broad + pk-filtered mix over
    the write keyspace), OFF = the verbatim per-sub oracle plane
    (``subs_columnar=False``, one shard), ON = the sharded columnar
    plane (defaults).  Throughput is measured from burst start to FULL
    matcher drain (commit AND deliver), so neither arm can bank
    undelivered matcher work outside the clock.  Same pairing/median
    discipline as the observability-plane gate: the MEDIAN per-pair
    on/off ratio gates at >= 0.95 — the refactor must not cost the
    write path what the fan-out work saves.  One subs-disabled run is
    recorded as context: the PLANE's absolute cost (real delivery
    work, scales with the standing population) vs no plane at all —
    context, not a gate, because delivered work is the product, not
    instrumentation."""
    import random
    import statistics
    import tempfile

    rng = random.Random(23)
    sub_sqls = [
        "SELECT * FROM tests",
        "SELECT text FROM tests",
        "SELECT count(*) FROM tests",
        "SELECT id, text FROM tests ORDER BY id LIMIT 10",
    ]
    seen = set(sub_sqls)
    while len(sub_sqls) < n_subs:
        pks = sorted(rng.sample(range(n_tx), 4))
        sql = ("SELECT id, text FROM tests WHERE id IN (%s)"
               % ", ".join(map(str, pks)))
        if sql not in seen:
            seen.add(sql)
            sub_sqls.append(sql)

    def on_setup(agent):
        for sql in sub_sqls:
            agent.subs.subscribe(sql)

    ARMS = {
        "off": {"subs_enabled": True, "subs_columnar": False,
                "subs_shards": 1},
        "on": {"subs_enabled": True},
    }
    pairs = []
    with tempfile.TemporaryDirectory(prefix="corro-subs-ab-") as d:
        for rep in range(reps):
            order = ("off", "on") if rep % 2 == 0 else ("on", "off")
            tx = {}
            for arm in order:
                r, _snap = _write_bench_once(
                    d, n_tx, writers, combined=True,
                    cfg_overrides=ARMS[arm],
                    tag=f"-subs-ab-{arm}{rep}",
                    setup=on_setup, drain_subs=True,
                )
                tx[arm] = r["tx_per_s"]
            pairs.append({
                "off_tx_per_s": tx["off"],
                "on_tx_per_s": tx["on"],
                "ratio": round(tx["on"] / max(tx["off"], 1e-9), 4),
            })
        no_plane, _ = _write_bench_once(
            d, n_tx, writers, combined=True, tag="-subs-ab-none",
        )
    ratio = statistics.median(p["ratio"] for p in pairs)
    return {
        "method": (
            f"paired in-run A/B, {reps} adjacent off/on pairs at the "
            "WRITE_BENCH headline shape (arm order alternating), "
            "median per-pair commit-and-deliver throughput ratio; "
            f"both arms carry {n_subs} standing subscriptions — "
            "off = per-sub oracle plane (subs_columnar=False, 1 "
            "shard), on = sharded columnar plane; wall runs to full "
            "matcher drain"
        ),
        "n_tx": n_tx,
        "writers": writers,
        "n_subs": n_subs,
        "pairs": pairs,
        "ratio": round(ratio, 4),
        "max_regression": max_regression,
        "no_plane_context_tx_per_s": no_plane["tx_per_s"],
        "pass": bool(ratio >= 1.0 - max_regression),
    }


def run_subs_bench(n_subs: int = 100_000, n_changes: int = 10_000,
                   swarm_subs: int = 256, swarm_writes: int = 1500,
                   ab: bool | None = None,
                   out_path: str = "SUBS_BENCH.json") -> dict:
    """Subscription fan-out benchmark (docs/pubsub.md): the sharded
    columnar matcher vs the per-sub oracle at the ``n_subs`` x
    ``n_changes`` headline with in-bench verdict parity, a mixed
    read/write/subscribe production swarm gated on p99 staleness,
    event-loop stall and converged-state parity (with the agent's own
    flight-recorder timeline attached), and a paired off/on A/B of the
    whole plane's write-path cost at the WRITE_BENCH headline shape."""
    import sys

    headline = _subs_matcher_headline(n_subs, n_changes)
    swarm = _subs_swarm(swarm_subs, swarm_writes)
    out = {
        "metric": "subs_matcher_columnar_speedup",
        # a speedup over DIVERGENT verdicts must not read as a clean
        # headline: any parity mismatch voids the value
        "value": (headline["speedup"]
                  if headline["parity"]["ok"] else None),
        "unit": "x",
        "conditions": (
            "delivered (subscription, pk) verdict pairs/s over one "
            "converged database and one change burst: sharded "
            "columnar pipeline (one kernel resolve + one row fetch "
            "per shard-wave, inverted predicate index) vs the per-sub "
            "oracle (one scoped SQL evaluation per subscription per "
            "wave, measured over a proportional subset with wave pks "
            "pre-intersected into each IN-list predicate — a head "
            "start the real per-sub path lacks, so the speedup is a "
            "floor); final per-(sub, pk) verdicts compared for "
            "equality over the subset; swarm = live agent under "
            "concurrent writers/readers/subscribe churn with 5 ms "
            "stall probe, 20 Hz staleness sampling and converged-"
            "state parity per subscription; overhead gate = paired "
            "A/B of the sharded columnar plane vs the per-sub oracle "
            "plane at identical standing load, commit-and-deliver "
            "wall (burst start to full matcher drain)"
        ),
        "headline": {"n_subs": n_subs, "n_changes": n_changes},
        "points": [headline],
        "parity": headline["parity"],
        "swarm": swarm,
    }
    if not headline["parity"]["ok"]:
        out["error"] = (
            "columnar/oracle verdict mismatch at the headline — "
            "speedup voided"
        )
    for gate, msg in (
        ("stall_gate", "swarm event-loop stall over budget"),
        ("staleness_gate", "swarm p99 staleness over SLO"),
    ):
        if not swarm[gate]["pass"]:
            out.setdefault("error", msg)
    if not swarm["parity_ok"]:
        out.setdefault(
            "error", "swarm converged-state parity mismatch"
        )
    if ab is None:
        # the A/B only resolves above the host noise floor at the 10k
        # write headline — smoke invocations skip it (same discipline
        # as the write bench's overhead gate)
        ab = n_changes >= 5000
    if ab:
        old_swi = sys.getswitchinterval()
        sys.setswitchinterval(0.002)
        try:
            out["overhead_gate"] = _subs_overhead_ab(10_000, 32)
        finally:
            sys.setswitchinterval(old_swi)
        if out["overhead_gate"]["pass"] is False:
            out.setdefault(
                "error",
                "subs overhead gate failed: sharded-columnar "
                "commit-and-deliver throughput regressed > 5% vs the "
                "per-sub oracle plane in paired A/B",
            )
    else:
        out["overhead_gate"] = {
            "pass": None,
            "skipped": "smoke scale: plane cost below noise floor; "
                       "gated at the 10k/32w headline",
        }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


# -- config #1: real 3-node devcluster ---------------------------------


def run_timeline_bench(n: int = 32,
                       out_path: str = "TIMELINE_N32.json") -> dict:
    """The flight-recorder timeline campaign (``sim/timeline.py``):
    recorder off/on paired A/B at the WRITE_BENCH headline shape first
    (the <5% overhead gate — the recorder must earn its default-on),
    then the live N-node partition-heal cell whose coverage trajectory
    gates against the kernel's per-tick curve."""
    import sys

    old_swi = sys.getswitchinterval()
    sys.setswitchinterval(0.002)
    try:
        gate = _write_overhead_ab(
            10_000, 32,
            off_overrides={"flight_interval_s": 0.0},
            plane_desc=(
                "plane = flight recorder (periodic metric snapshots "
                "+ typed event journal)"
            ),
        )
    finally:
        sys.setswitchinterval(old_swi)
    from corrosion_tpu.sim.timeline import run_timeline

    return asyncio.run(run_timeline(
        n=n, out_path=out_path, overhead_gate=gate,
    ))


async def _devcluster3() -> dict:
    from corrosion_tpu.agent.testing import wait_for
    from corrosion_tpu.devcluster import Topology, run_inprocess

    topo = Topology.parse("a -> b\na -> c")
    agents = await run_inprocess(topo)
    a, b, c = (agents[n] for n in "abc")
    try:
        await wait_for(
            lambda: all(len(x.members.alive()) == 2 for x in (a, b, c)),
            timeout=30,
        )
        n_rows = 50
        t0 = time.perf_counter()
        # concurrent conflicting writes: inserts on a, LWW-racing
        # updates of the same pks on b
        a.execute_transaction([
            ["INSERT INTO tests (id, text) VALUES (?, ?)", [i, f"a{i}"]]
            for i in range(n_rows)
        ])
        b.execute_transaction([
            ["INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
             [i, f"b{i}"]]
            for i in range(0, n_rows, 2)
        ])

        def table(x):
            return x.storage.read_query(
                "SELECT id, text FROM tests ORDER BY id")[1]

        def converged():
            ta = table(a)
            return (len(ta) == n_rows and table(b) == ta and
                    table(c) == ta)

        await wait_for(converged, timeout=60)
        wall = time.perf_counter() - t0
        msgs = sum(
            x.metrics.get_counter("corro_broadcast_sent_total")
            + x.metrics.get_counter("corro_sync_served_total")
            for x in (a, b, c)
        )
        return {
            "metric": "devcluster3_lww_convergence_wall",
            "value": round(wall, 3),
            "unit": "s",
            "n_nodes": 3,
            "rows": n_rows,
            "msgs_per_node_mean": round(msgs / 3, 1),
        }
    finally:
        for x in (a, b, c):
            await x.stop()


# -- sweep-point accounting --------------------------------------------


def _device_bitmap_budget() -> tuple:
    """Per-device byte budget for the exact sampler's dense ``sent_to``
    bitmap, derived from the backend's REPORTED device memory (half of
    it: the other half stays for XLA temps, stats and the small state).
    When the backend exposes no memory stats (CPU), the host's
    ``/proc/meminfo`` MemAvailable split across the devices that share
    it stands in (``sim/calibrate.py host_memory_budget_bytes`` — the
    same derivation ``frontier_seed_batch`` uses for the host-sharded
    kernel), with the historical 256 MiB constant as the last resort.
    Returns (bytes, source) so artifacts can record where the number
    came from."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit"
        )
        if limit:
            return int(limit) // 2, "device_memory_stats/2"
    except Exception:  # noqa: BLE001 - backend-dependent API surface
        pass
    from corrosion_tpu.sim.calibrate import host_memory_budget_bytes

    try:
        budget = host_memory_budget_bytes(jax.device_count())
    except Exception:  # noqa: BLE001 - /proc surface varies by platform
        budget = None
    if budget:
        return int(budget), "host_meminfo/2/devices"
    return 256 << 20, "fallback_constant_256MiB"


def _exact_kernel_plan(n: int):
    """(kernel, mesh) dispatch for the exact sampler at ``n`` nodes:
    ``dense`` (single-chip bitpacked bitmap) while the [N, N/8] bitmap
    fits the per-device budget, ``sharded-dense`` (bitmap row-sharded
    over a ``nodes`` mesh) while a shard of it does, and ``sparse``
    (the frontier kernel: capped recent-target rings, O(N*budget*k)
    state) beyond — the only representation that reaches N=1M.  All
    three are bitwise-equal per seed (tests/test_frontier.py,
    tests/test_sharding.py), so dispatch never moves the numbers."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    budget, src = _device_bitmap_budget()
    bitmap = n * (-(-n // 8))
    if src.startswith("host_meminfo"):
        # every "device" is a virtual CPU device sharing ONE RAM pool:
        # row-sharding the bitmap buys zero memory headroom, so dense
        # dispatch asks whether the whole bitmap (plus its donated
        # double during the scan) fits the per-device share, and beyond
        # that goes straight to sparse
        if 2 * bitmap <= budget:
            return "dense", None
        return "sparse", None
    if bitmap < budget:
        return "dense", None
    d = jax.device_count()
    if d >= 2 and n % d == 0 and bitmap // d < budget:
        return "sharded-dense", Mesh(np.array(jax.devices()), ("nodes",))
    return "sparse", None


def _frontier_exact_cfg(n: int, partitioned: bool):
    """The headline protocol family at ``n`` nodes for the exact
    sampler (shared by the main sweeps and ``--frontier``).  Beyond
    256k the scan chunk halves so the compile-warming chunk doesn't
    cost half a measured run (chunk granularity only moves the
    convergence-CHECK cadence, never the per-seed statistics)."""
    from corrosion_tpu.sim.calibrate import HeadlineExactConfig

    return HeadlineExactConfig(
        n_nodes=n, fanout=4, ring0_size=256,
        max_transmissions=8, loss=0.05,
        partition_blocks=2 if partitioned else 1,
        heal_tick=12 if partitioned else 0,
        sync_interval=8, sync_peers=1,
        max_ticks=192, chunk_ticks=16 if n <= 256_000 else 8,
    )


def _run_exact_planned(ecfg, seeds: int, kernel=None, mesh=None) -> dict:
    """Warm (compile at the real batch shape) + measured
    ``run_exact_headline`` under the budget-derived kernel plan; the
    result carries the kernel tag for the artifact.  ``kernel`` may be
    a plan tag (``sharded-`` prefixed): the runner takes the base
    representation and re-derives the prefix from ``mesh``.
    ``"host-sparse"`` selects the MULTI-HOST frontier layout (mesh must
    carry a ``hosts`` axis)."""
    from corrosion_tpu.sim.calibrate import run_exact_headline

    if kernel is None:
        kernel, mesh = _exact_kernel_plan(ecfg.n_nodes)
    host_sharded = kernel == "host-sparse"
    base = "sparse" if kernel.endswith("sparse") else "dense"
    run_exact_headline(ecfg, n_seeds=seeds, seed=1, mesh=mesh,
                       warm_chunks=1, kernel=base,
                       host_sharded=host_sharded)
    return run_exact_headline(ecfg, n_seeds=seeds, seed=0, mesh=mesh,
                              kernel=base, host_sharded=host_sharded)


def _frontier_point(n: int, res: dict) -> dict:
    """One exact-sampler sweep row (shared by the lossonly sweep and
    the frontier artifact — one hand-maintained schema, not two).
    Every row records the bitmap budget its kernel dispatch was derived
    from, so a reader can re-check the dense/sharded/sparse choice."""
    budget, budget_src = _device_bitmap_budget()
    row = {
        "n": n,
        "ticks_p50": res["ticks_p50"],
        "ticks_p99": res["ticks_p99"],
        "msgs_per_node_mean": round(res["msgs_per_node_mean"], 2),
        "msgs_per_node_p99": round(res["msgs_per_node_p99"], 2),
        "converged_frac": res["converged_frac"],
        "delivery_model": "exact-rejection-sampler",
        "kernel": res.get("kernel"),
        "n_seeds": res["n_seeds"],
        "seed_batch": res.get("seed_batch"),
        "n_shards": res.get("n_shards"),
        "bitmap_budget_bytes": budget,
        "budget_source": budget_src,
        "wall_s": round(res["wall_s"], 2),
    }
    if res.get("n_hosts", 1) > 1:
        row["n_hosts"] = res["n_hosts"]
    return row


def _frontier_perf_gate_100k(sweep_100k: dict, n_seeds: int,
                             keys: tuple) -> dict:
    """The N=100k dense-vs-sparse perf + stats gate of the frontier
    artifact; ``sweep_100k`` is the sweep's already-measured 100k
    point, reused for whichever arm its kernel matches so the priciest
    representation never runs twice."""
    import jax

    cfg100 = _frontier_exact_cfg(100_000, partitioned=False)
    dense_kernel, dense_mesh = _exact_kernel_plan(100_000)
    if dense_kernel == "sparse":
        # budget put even 100k past the dense representation on this
        # backend: force the mesh-sharded dense arm if a mesh exists,
        # else single-chip dense (RAM permitting)
        import numpy as np
        from jax.sharding import Mesh

        d = jax.device_count()
        if d >= 2 and 100_000 % d == 0:
            dense_kernel, dense_mesh = "sharded-dense", Mesh(
                np.array(jax.devices()), ("nodes",)
            )
        else:
            dense_kernel, dense_mesh = "dense", None
    if sweep_100k["kernel"] in ("sparse", "sharded-sparse"):
        sparse_res = sweep_100k
    else:
        sparse_res = _frontier_point(
            100_000,
            _run_exact_planned(cfg100, n_seeds, kernel="sparse"),
        )
    if sweep_100k["kernel"] == dense_kernel:
        dense_res = sweep_100k
    else:
        dense_res = _frontier_point(
            100_000,
            _run_exact_planned(cfg100, n_seeds, kernel=dense_kernel,
                               mesh=dense_mesh),
        )
    ratio = sparse_res["wall_s"] / max(dense_res["wall_s"], 1e-9)
    return {
        "n": 100_000,
        "n_seeds": n_seeds,
        "dense_kernel": dense_res["kernel"],
        "dense_wall_s": dense_res["wall_s"],
        "sparse_kernel": sparse_res["kernel"],
        "sparse_wall_s": sparse_res["wall_s"],
        "sparse_over_dense": round(ratio, 3),
        # the flat perm-kernel 100k headline this repo carried since
        # PR 1 (~20.7 s wall, BENCH_r01-r05) — context for readers;
        # the gate itself is same-host dense-vs-sparse
        "reference_dense_headline_wall_s": 20.7,
        "pass": bool(ratio <= 1.0),
        "stats_equal": all(
            sparse_res[k] == dense_res[k] for k in keys
        ),
    }


def _frontier_multi_host_gate(measured_weights, wan_latency_ticks: int,
                              n: int = 256, ticks: int = 10,
                              n_seeds: int = 2) -> dict:
    """In-record multi-host exactness witness: the host-sharded
    frontier step, run tick-by-tick on the emulated host mesh, must
    leave EVERY state leaf (infected, tx, next_send, ring, msgs,
    pending) bitwise equal to the single-chip ``frontier_exact_tick``
    — across the headline protocol shape and BOTH new topology
    families (measured-RTT ring, tick-quantized WAN latency).  The
    committed artifact carries its own dispatch-invariance proof for
    the kernel that produced the 10M headline; the seeded-corruption
    negative control lives in tests/test_sharding.py."""
    from dataclasses import replace as _replace

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from corrosion_tpu.models.sharded import sharded_frontier_host_step
    from corrosion_tpu.sim.calibrate import (
        HeadlineExactConfig,
        frontier_exact_init,
        frontier_exact_tick,
        frontier_host_shardings,
    )

    n_hosts = max(h for h in (1, 2, 4, 8)
                  if h <= jax.device_count() and n % (8 * h) == 0)
    base_cfg = HeadlineExactConfig(
        n_nodes=n, fanout=4, ring0_size=16, max_transmissions=8,
        loss=0.05, sync_interval=4, backoff_ticks=0.5, max_ticks=64,
    )
    families = {
        "headline": {},
        "measured_ring": {
            "topology": "measured_ring",
            "rtt_tier_weights": tuple(measured_weights),
        },
        "wan_latency": {
            "topology": "wan_two_region",
            "wan_cross_loss": 0.0,
            "wan_latency_ticks": wan_latency_ticks,
        },
    }
    mesh = Mesh(np.array(jax.devices()[:n_hosts]), ("hosts",))
    fields = ("infected", "tx", "next_send", "ring", "msgs", "tick",
              "pending")
    out = {"n": n, "n_hosts": n_hosts, "ticks": ticks,
           "n_seeds": n_seeds, "fields_compared": list(fields)}
    ok_all = True
    for fam, overrides in families.items():
        cfg = _replace(base_cfg, **overrides)
        base = [jax.random.PRNGKey(31 + s) for s in range(n_seeds)]
        refs = [
            frontier_exact_init(cfg, jax.random.fold_in(kk, 2**20))
            for kk in base
        ]
        batched = jax.vmap(
            lambda kk: frontier_exact_init(
                cfg, jax.random.fold_in(kk, 2**20)
            )
        )(jnp.stack(base))
        batched = jax.device_put(batched, frontier_host_shardings(mesh))
        step = sharded_frontier_host_step(mesh, cfg)
        ok = True
        for t in range(ticks):
            keys_t = jnp.stack([jax.random.fold_in(kk, t) for kk in base])
            refs = [
                frontier_exact_tick(r, jax.random.fold_in(kk, t), cfg)
                for r, kk in zip(refs, base)
            ]
            batched = step(batched, keys_t)
            for s in range(n_seeds):
                for field in fields:
                    ok &= bool(np.array_equal(
                        np.asarray(getattr(batched, field)[s]),
                        np.asarray(getattr(refs[s], field)),
                    ))
        # the gate must witness a live epidemic, not a trivially-equal
        # no-progress trajectory (full convergence within the compared
        # ticks is fine — the slower families stay partial)
        alive = float(np.asarray(batched.infected).mean()) > 2.0 / n
        out[fam] = {"bitwise_equal": ok, "epidemic_live": alive}
        ok_all &= ok and alive
    out["pass"] = ok_all
    return out


#: tier weights the measured_ring cells fall back to when no captured
#: topology artifact exists (shape of the capture campaign's output:
#: most nodes in the mid tiers, a small far tail)
_MEASURED_WEIGHTS_FALLBACK = (0, 0, 2, 2, 6, 1)


def run_capture_topology(out_path: str = "TOPOLOGY_MEASURED.json",
                         n: int = 24, seed: int = 7,
                         sim_s: float = 30.0) -> dict:
    """Deterministic measured-topology capture campaign: N real agents
    on the virtual-time cluster with a ring-distance per-pair RTT
    (2 ms adjacent, +8 ms per hop), probed long enough for every
    Members ring to fill its RTT windows, then aggregated with
    ``capture_rtt_topology`` into the measured_ring topology JSON that
    ``--frontier`` (and ``HeadlineExactConfig(rtt_tier_weights=...)``)
    consume.  Same (n, seed, sim_s) -> byte-identical artifact.  The
    single-node path of the same export is the agent admin
    ``corro-tpu rtt dump`` command."""
    from corrosion_tpu.sim.vcluster import (
        VirtualCluster,
        capture_rtt_topology,
    )

    t0 = time.perf_counter()

    def ring_rtt(i: int, j: int) -> float:
        d = min(abs(i - j), n - abs(i - j))
        return 0.002 + 0.008 * d

    c = VirtualCluster(n, seed=seed, link_rtt_fn=ring_rtt)
    try:
        c.run_for(sim_s)
        topo = capture_rtt_topology(c)
    finally:
        c.close()
    topo["capture"] = {
        "campaign": "vcluster_ring_distance",
        "n": n,
        "seed": seed,
        "sim_s": sim_s,
        "link_rtt_s": "0.002 + 0.008 * ring_distance",
        "wall_s": round(time.perf_counter() - t0, 2),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(topo), f, indent=2)
            f.write("\n")
    return topo


def run_frontier_bench(
    out_path: str = "BENCH_FRONTIER.json",
    ns=(1000, 16000, 100000, 256000, 1000000, 10_000_000),
    n_seeds: int = 4,
    topo_n: int = 100_000,
    host_seeds: int = 2,
    n_hosts: int = 2,
    topo_names=None,
    topology_json: str = None,
    wan_latency_ticks: int = 2,
) -> dict:
    """The frontier-sparse BENCH headline: the exact sampler's p99
    convergence ticks + msgs/node swept through N=10M, every point
    tagged with the kernel that produced it (dense / sharded-dense /
    sparse per the memory-derived budget; the dense [N, N/8]
    ``sent_to`` bitmap tops out around 100k — ~125 GB at 1M vs the
    ring's 128 MB), plus:

    * the MULTI-HOST headline: beyond the 1M single-host point the
      sweep switches to the host-sharded frontier kernel (``n_hosts``
      emulated hosts on the virtual-device mesh) — per-host row shards
      of every O(N) leaf, and ONLY the rejection loop's bitpacked
      validity deltas crossing the host fabric per tick;
    * an EXACTNESS gate: the sparse runner's per-seed rank statistics
      equal the dense runner's at a small N (the committed artifact's
      own witness that kernel dispatch cannot move the numbers; the
      bitwise per-tick contract is pinned by tests/test_frontier.py);
    * a MULTI-HOST gate: the host-sharded step bitwise-equal to the
      single-chip frontier kernel at N=256 across the headline shape
      and both new topology families (measured ring, WAN latency);
    * a PERF gate at N=100k: the sparse kernel's wall must not exceed
      the dense kernel's on the same host at matched seeds (the
      acceptance bound — the representation change must not cost the
      existing scale anything);
    * one sweep point per scenario topology beyond uniform fanout
      (heterogeneous-RTT ring, two-region WAN, measured-RTT ring from
      the captured TOPOLOGY_MEASURED.json distribution, tick-quantized
      WAN latency queues) at ``topo_n``.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    budget, budget_src = _device_bitmap_budget()
    t_total = time.perf_counter()
    _point = _frontier_point

    # measured_ring weights: an explicit --topology-json wins, else the
    # committed capture-campaign artifact, else the documented fallback
    here = os.path.dirname(os.path.abspath(__file__))
    measured = _committed_json(
        topology_json or os.path.join(here, "TOPOLOGY_MEASURED.json")
    )
    if measured and measured.get("weights"):
        m_weights = tuple(int(w) for w in measured["weights"])
        m_src = topology_json or "TOPOLOGY_MEASURED.json"
    else:
        m_weights = _MEASURED_WEIGHTS_FALLBACK
        m_src = "fallback_default"

    points = []
    for n in ns:
        ecfg = _frontier_exact_cfg(n, partitioned=False)
        kernel = mesh = None
        seeds_n = n_seeds
        if n > 1_000_000:
            # the multi-host headline: host-sharded frontier kernel on
            # an emulated n_hosts mesh (forced minimum H=2 — the point
            # exists to run the delta-only exchange layer, and on a
            # shared-RAM virtual mesh more hosts only multiply the
            # replicated work), fewer seeds (each costs 10M-node ticks)
            seeds_n = host_seeds
            if jax.device_count() < n_hosts or n % (8 * n_hosts):
                points.append({
                    "n": n, "error": f"host-sparse needs {n_hosts} "
                    f"devices and n % (8 * {n_hosts}) == 0",
                })
                continue
            kernel = "host-sparse"
            mesh = Mesh(np.array(jax.devices()[:n_hosts]), ("hosts",))
        try:
            res = _run_exact_planned(ecfg, seeds_n, kernel=kernel,
                                     mesh=mesh)
        except Exception as e:  # noqa: BLE001 - surfaced in the record
            points.append({"n": n, "error": f"{type(e).__name__}: {e}"})
            continue
        points.append(_point(n, res))

    # exactness gate: dense vs sparse runner stats at a small N — the
    # artifact's own dispatch-invariance witness
    from corrosion_tpu.sim.calibrate import run_exact_headline

    gate_cfg = _frontier_exact_cfg(2000, partitioned=False)
    keys = ("converged_frac", "ticks_p50", "ticks_p99",
            "msgs_per_node_mean", "msgs_per_node_p99")
    dense_small = run_exact_headline(gate_cfg, n_seeds=3, seed=0,
                                     kernel="dense")
    sparse_small = run_exact_headline(gate_cfg, n_seeds=3, seed=0,
                                      kernel="sparse")
    exactness = {
        "n": 2000,
        "n_seeds": 3,
        "keys_compared": list(keys),
        "dense": {k: dense_small[k] for k in keys},
        "sparse": {k: sparse_small[k] for k in keys},
        "pass": all(dense_small[k] == sparse_small[k] for k in keys),
    }

    # perf gate at 100k: sparse wall vs the dense representation's wall
    # on THIS host at matched seeds (the dense arm is whatever dense
    # kernel the budget allows here — single-chip or mesh-sharded).
    # Guarded like every sweep point: a gate-arm failure (e.g. a
    # single-device host OOMing on the forced dense bitmap) must not
    # discard the already-measured sweep — it lands in the record and
    # voids the artifact via the error field instead
    perf = None
    sparse_100k = next(
        (p for p in points if p.get("n") == 100_000 and "error" not in p),
        None,
    )
    if sparse_100k is not None:
        try:
            perf = _frontier_perf_gate_100k(sparse_100k, n_seeds, keys)
        except Exception as e:  # noqa: BLE001 - surfaced in the record
            perf = {"n": 100_000, "error": f"{type(e).__name__}: {e}",
                    "pass": None}
    elif 100_000 in ns:
        # the gate is mandatory: a failed 100k sweep point must void
        # the artifact via the error field, not silently skip the gate
        perf = {"n": 100_000, "pass": None, "error":
                "no successful 100k sweep point to gate against"}

    # multi-host exactness gate: host-sharded step bitwise vs the
    # single-chip frontier kernel across the headline shape and BOTH
    # new topology families (guarded: a gate crash voids the artifact
    # via the error field, never discards the measured sweep)
    try:
        multi_host = _frontier_multi_host_gate(
            m_weights, wan_latency_ticks
        )
    except Exception as e:  # noqa: BLE001 - surfaced in the record
        multi_host = {"error": f"{type(e).__name__}: {e}", "pass": False}

    # scenario diversity beyond uniform fanout: one sweep point each —
    # the two PR-15 families plus the measured-RTT ring (data-driven
    # tier map from the capture campaign) and the WAN latency-queue
    # family (delayed cross-region delivery, zero extra loss)
    topo_families = {
        "het_ring": {"topology": "het_ring"},
        "wan_two_region": {"topology": "wan_two_region"},
        "measured_ring": {
            "topology": "measured_ring",
            "rtt_tier_weights": m_weights,
        },
        "wan_latency": {
            "topology": "wan_two_region",
            "wan_cross_loss": 0.0,
            "wan_latency_ticks": wan_latency_ticks,
        },
    }
    if topo_names:
        topo_families = {
            k: v for k, v in topo_families.items() if k in topo_names
        }
    topologies = {}
    for topo, overrides in topo_families.items():
        from dataclasses import replace as _replace

        tcfg = _replace(
            _frontier_exact_cfg(topo_n, partitioned=False),
            **overrides,
        )
        try:
            res = _run_exact_planned(tcfg, n_seeds, kernel="sparse")
        except Exception as e:  # noqa: BLE001 - surfaced in the record
            topologies[topo] = {
                "n": topo_n, "error": f"{type(e).__name__}: {e}",
            }
            continue
        row = _point(topo_n, res)
        row["topology"] = tcfg.topology
        if topo == "het_ring":
            row["rtt_tiers"] = tcfg.rtt_tiers
        elif topo == "measured_ring":
            row["rtt_tier_weights"] = list(m_weights)
            row["weights_source"] = m_src
        elif topo == "wan_latency":
            row["wan_blocks"] = tcfg.wan_blocks
            row["wan_latency_ticks"] = tcfg.wan_latency_ticks
            row["wan_cross_loss"] = tcfg.wan_cross_loss
        else:
            row["wan_blocks"] = tcfg.wan_blocks
            row["wan_cross_loss"] = tcfg.wan_cross_loss
        topologies[topo] = row

    headline = next(
        (p for p in points
         if p.get("n") == max(ns) and "error" not in p), None
    )
    out = {
        "metric": "epidemic_exact_frontier_sweep_vs_n",
        "value": headline["ticks_p99"] if headline else None,
        "unit": "ticks",
        "conditions": (
            "headline protocol family (fanout 4, ring0 256, budget 8, "
            "5% loss, sync every 8 ticks, NO partition), the exact "
            "sent_to-excluding sampler at every N with per-point "
            "kernel dispatch from the device-memory-derived bitmap "
            "budget; p99s are rank statistics over the per-seed "
            "convergence ticks"
        ),
        "kernel_budget": {
            "bitmap_budget_bytes": budget,
            "source": budget_src,
            "devices": jax.device_count(),
            "backend": jax.default_backend(),
        },
        "points": points,
        "headline": headline,
        "exactness_gate": exactness,
        "multi_host_gate": multi_host,
        "perf_gate_100k": perf,
        "topologies": topologies,
        "wall_s_total": round(time.perf_counter() - t_total, 2),
    }
    errs = []
    if headline is None:
        errs.append(f"no N={max(ns)} headline point")
    if not exactness["pass"]:
        errs.append("dense/sparse runner stats diverged")
    if not multi_host.get("pass"):
        errs.append("multi-host gate failed")
    if perf is not None:
        if "error" in perf:
            errs.append(f"100k perf gate failed to run: {perf['error']}")
        else:
            if not perf["pass"]:
                errs.append(
                    "sparse 100k wall exceeded the dense kernel's"
                )
            if not perf["stats_equal"]:
                errs.append("dense/sparse 100k rank stats diverged")
    if errs:
        out["error"] = "; ".join(errs)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(_sanitize(out), f, indent=2)
            f.write("\n")
    return out


def _exact_block(exact: dict) -> dict:
    """The exact-sampler sub-record of a sweep row (shared by both
    sweeps): real rank statistics over the seed-parallel runs, plus the
    batching/sharding/kernel facts that produced them."""
    return {
        "delivery_model": "exact-rejection-sampler",
        "kernel": exact.get("kernel"),
        "msgs_per_node_mean": round(exact["msgs_per_node_mean"], 2),
        "msgs_per_node_p99": round(exact["msgs_per_node_p99"], 2),
        "ticks_p50": exact["ticks_p50"],
        "ticks_p99": exact["ticks_p99"],
        "converged_frac": exact["converged_frac"],
        "n_seeds": exact["n_seeds"],
        "seed_batch": exact.get("seed_batch"),
        "n_shards": exact.get("n_shards"),
        "wall_s": round(exact["wall_s"], 2),
    }


def _strip_unfilled_hops(row: dict) -> dict:
    """A row must not advertise a stat it doesn't fill: hop percentiles
    whose rank exceeds the measured broadcast coverage (e.g. a p99 when
    5% loss + partitions pull coverage under 99%) are DROPPED from the
    record instead of published as null — ``hops_broadcast_frac`` stays
    whenever hop tracking ran, so the reader can see how much depth
    coverage the surviving percentiles rest on."""
    for hk in ("hops_p50", "hops_p99", "hops_broadcast_frac"):
        if hk in row and row[hk] is None:
            del row[hk]
    return row


def _sweep_point(n: int, s: dict, exact: dict | None = None) -> dict:
    """One truthful sweep row: every msgs/hops value is measured (with
    its delivery model named) — unfilled hop percentiles are dropped,
    not published as null.  ``exact`` is the bitpacked exact-sampler
    measurement at the SAME n and protocol (sim/calibrate.py
    run_exact_headline) — MEASURED at every sweep N including 100k,
    with seed-parallel batches over the device mesh."""
    row = {
        "n": n,
        "ticks_p50": s["ticks_p50"],
        "ticks_p99": s["ticks_p99"],
        "msgs_per_node_mean": round(s["msgs_per_node_mean"], 2),
        "delivery_model": "perm-fanout-lower-bound",
        "hops_p50": s.get("hops_p50"),
        "hops_p99": s.get("hops_p99"),
        "hops_broadcast_frac": s.get("hops_broadcast_frac"),
        "converged_frac": s["converged_frac"],
        "wall_s": round(s["wall_s"], 2),
    }
    _strip_unfilled_hops(row)
    if exact is not None:
        row["exact"] = _exact_block(exact)
    return row


# -- north-star exactness: deterministic bit-match ---------------------


def _bitmatch() -> dict:
    from corrosion_tpu.sim.bitmatch import run_bitmatch

    here = os.path.dirname(os.path.abspath(__file__))
    out = {"metric": "bitmatch_sim_vs_agents", "unit": "bool"}
    all_ok = True
    # the HEADLINE protocol shape (ring0-first fanout, 5% loss,
    # anti-entropy sync every 8 ticks — the parameter family of the
    # benchmarked epidemic), not a simplified fanout-only protocol
    for n, ring0 in ((64, 8), (256, 16)):
        t0 = time.perf_counter()
        r = run_bitmatch(n, writes=2, seed=0,
                         loss=0.05, ring0_size=ring0, sync_interval=8,
                         out_path=os.path.join(here, f"BITMATCH_N{n}.json"))
        all_ok &= r["bitmatch"]
        out[f"n{n}"] = {
            "bitmatch": r["bitmatch"],
            "protocol": {"loss": 0.05, "ring0_size": ring0,
                         "sync_interval": 8},
            "ticks": [w["ticks_compared"] for w in r["per_write"]],
            "converged": [w["converged_tick_agents"]
                          for w in r["per_write"]],
            "first_mismatch": [w["first_mismatch_tick"]
                               for w in r["per_write"]],
            "wall_s": round(time.perf_counter() - t0, 2),
        }
    out["value"] = 1.0 if all_ok else 0.0
    if not all_ok:
        out["error"] = "sim/agent traces diverged"
    return out


# -- config #2: 64-node SWIM churn -------------------------------------


def _churn64() -> dict:
    from corrosion_tpu.sim.churn import ChurnConfig, run_churn

    stats = run_churn(ChurnConfig(n_nodes=64))
    out = {
        "metric": "swim_churn_64_detect_latency",
        "value": stats["detect_latency"],
        "unit": "ticks",
        "n_nodes": 64,
        "rejoin_latency_ticks": stats["rejoin_latency"],
        "msgs_per_node_mean": round(stats["msgs_per_node_mean"], 1),
        "msgs_per_node_per_tick": round(
            stats["msgs_per_node_per_tick"], 2),
        "wall_s": round(stats["wall_s"], 3),
    }
    if stats["detect_latency"] is None or stats["rejoin_latency"] is None:
        out["error"] = "churn cycle did not complete in max_ticks"
    return out


# -- config #4: seq-chunked anti-entropy reassembly --------------------


def _timed_sim(name: str, run, n_seeds: int, headline: bool = False,
               extra: dict | None = None) -> dict:
    """Shared scaffolding for the sim configs: a warm run pays compile,
    the measured run reuses it; non-finite ticks become null."""
    t0 = time.perf_counter()
    run(seed=1)  # compile + warm
    compile_and_first = time.perf_counter() - t0
    stats = run(seed=0)

    ticks_p99 = stats["ticks_p99"]
    out = {
        "metric": name,
        "value": round(stats["wall_s"], 3),
        "unit": "s",
        "n_nodes": stats["n_nodes"],
        "ticks_p99": None if not (ticks_p99 < float("inf")) else ticks_p99,
        "ticks_p50": stats.get("ticks_p50"),
        "msgs_per_node_mean": round(stats["msgs_per_node_mean"], 1),
        "hops_p50": stats.get("hops_p50"),
        "hops_p99": stats.get("hops_p99"),
        "hops_broadcast_frac": stats.get("hops_broadcast_frac"),
        "converged_frac": stats["converged_frac"],
        "n_seeds": n_seeds,
        "compile_s": round(compile_and_first - stats["wall_s"], 1),
    }
    out.update(extra or {})
    _strip_unfilled_hops(out)
    if stats["converged_frac"] < 1.0 and not headline:
        out["error"] = "did not converge"
    return out


def _anti_entropy(n_seeds: int) -> dict:
    """Config #4: 10k nodes reassemble one chunked changeset purely
    through sync rounds (broadcast disabled): budgeted chunk sessions,
    2% chunk loss, out-of-order arrival, gap healing — the seq-bitmap
    kernel."""
    from corrosion_tpu.sim import AntiEntropyConfig, run_anti_entropy_seeds

    cfg = AntiEntropyConfig()  # 10k nodes, 64 seqs, budget 4, loss 2%
    return _timed_sim(
        "anti_entropy_seq_reassembly_10k_wall",
        lambda seed: run_anti_entropy_seeds(cfg, n_seeds=n_seeds, seed=seed),
        n_seeds,
        extra={"n_seqs": cfg.n_seqs, "chunk_loss": cfg.loss},
    )


# -- configs #3/#5: epidemic kernel ------------------------------------


def _epidemic(name: str, cfg, n_seeds: int, headline: bool = False) -> dict:
    from corrosion_tpu.sim import run_epidemic_seeds

    return _timed_sim(
        name,
        lambda seed: run_epidemic_seeds(cfg, n_seeds=n_seeds, seed=seed),
        n_seeds,
        headline=headline,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000,
                    help="headline config #5 cluster size")
    ap.add_argument("--seeds", type=int, default=32)
    ap.add_argument("--rows", type=int, default=8,
                    help="CRDT cells per changeset (configs 3/5; config "
                         "4 sizes its payload in seqs, see "
                         "AntiEntropyConfig.n_seqs)")
    ap.add_argument("--config", default="all",
                    help="1-5 to run a single config, default all")
    ap.add_argument("--check", action="store_true",
                    help="fast correctness pass (small N, config 5 only)")
    ap.add_argument("--calibrate-msgs", action="store_true",
                    help="regenerate CALIB_MSGS.json (exact sampler at "
                         "1k-16k vs perm fanout; ~3-5 min) and exit")
    ap.add_argument("--frontier", action="store_true",
                    help="run the frontier-sparse exact-sampler sweep "
                         "through N=10M (per-point kernel dispatch "
                         "from the memory-derived bitmap budget; the "
                         "10M headline runs the multi-host frontier "
                         "kernel with delta-only cross-host exchange "
                         "on an emulated host mesh; dense-vs-sparse "
                         "exactness, multi-host bitwise and 100k perf "
                         "gates; het-RTT ring, two-region WAN, "
                         "measured-RTT ring and WAN-latency topology "
                         "points), write BENCH_FRONTIER.json, and "
                         "exit")
    ap.add_argument("--topology", default=None,
                    help="comma-separated subset of the --frontier "
                         "topology families (het_ring, wan_two_region, "
                         "measured_ring, wan_latency; default all)")
    ap.add_argument("--topology-json", default=None,
                    help="measured-topology JSON (TOPOLOGY_MEASURED."
                         "json schema, e.g. from `corro-tpu rtt dump "
                         "--out` or --capture-topology) whose weights "
                         "drive the --frontier measured_ring cells "
                         "(default: the committed TOPOLOGY_MEASURED."
                         "json, then a built-in fallback)")
    ap.add_argument("--capture-topology", action="store_true",
                    help="run the deterministic virtual-cluster RTT "
                         "capture campaign (ring-distance per-pair "
                         "latency, real agents, real SWIM probes), "
                         "write TOPOLOGY_MEASURED.json, and exit")
    ap.add_argument("--chaos", action="store_true",
                    help="run the N=32 chaos soak (live cluster under "
                         "the headline fault family vs the sim's "
                         "degraded-mode prediction), write "
                         "CHAOS_N32.json, and exit")
    ap.add_argument("--chaos-nodes", type=int, default=32,
                    help="cluster size for --chaos")
    ap.add_argument("--scenarios", action="store_true",
                    help="run the adversarial scenario matrix (clock "
                         "skew / one-way partition / slow IO + loop "
                         "stall / equivocating peer / compound) on a "
                         "live cluster next to the kernel prediction, "
                         "gated on convergence + no-divergence, write "
                         "SCENARIOS_N32.json, and exit")
    ap.add_argument("--scenario-nodes", type=int, default=32,
                    help="cluster size for --scenarios")
    ap.add_argument("--scenario-families", default=None,
                    help="comma-separated subset of scenario families "
                         "(default: all)")
    ap.add_argument("--virtual-time", action="store_true",
                    help="run --scenarios / --timeline on the "
                         "virtual-time cluster (sim/vcluster.py): "
                         "every agent timer advances by event-queue "
                         "pops, so the full campaign stack runs at "
                         "N=512-1024 in seconds of wall time; adds "
                         "the scale-only families (restart storm, "
                         "hostile-fraction sweeps, compound "
                         "crash-composed cells) and, for --timeline, "
                         "the N=32 virtual-vs-real parity cell")
    ap.add_argument("--n", type=int, default=None,
                    help="cluster size shorthand: overrides "
                         "--scenario-nodes / --timeline-nodes "
                         "(default 512 under --virtual-time)")
    ap.add_argument("--timeline", action="store_true",
                    help="run the flight-recorder timeline campaign "
                         "(live N=32 partition-heal trajectory gated "
                         "against the kernel's per-tick coverage "
                         "curve, plus the recorder off/on overhead "
                         "A/B), write TIMELINE_N32.json, and exit")
    ap.add_argument("--timeline-nodes", type=int, default=32,
                    help="cluster size for --timeline")
    ap.add_argument("--obs", action="store_true",
                    help="run the observability soak (live cluster "
                         "measuring its OWN convergence via telemetry, "
                         "gated ±15%% against harness ground truth, "
                         "next to the kernel prediction), write "
                         "OBS_N32.json, and exit")
    ap.add_argument("--obs-nodes", type=int, default=32,
                    help="cluster size for --obs")
    ap.add_argument("--obs-writes", type=int, default=40,
                    help="workload size for --obs")
    ap.add_argument("--apply", action="store_true",
                    help="run the per-change vs batched CRDT apply "
                         "microbenchmark (1k/10k changes, cold+warm), "
                         "write APPLY_BENCH.json, and exit")
    ap.add_argument("--sync", action="store_true",
                    help="run the per-version vs batched sync SERVE "
                         "microbenchmark (full-range backfill need, "
                         "cold+warm, parity-checked, event-loop stall, "
                         "live two-node backfill), write "
                         "SYNC_BENCH.json, and exit")
    ap.add_argument("--sync-versions", type=int, default=10_000,
                    help="backfill size for --sync")
    ap.add_argument("--boot", action="store_true",
                    help="run the bootstrap-recovery benchmark (fresh "
                         "node catching up a 10k-version foreign "
                         "history change-by-change vs snapshot "
                         "install + tail sync, recovery wall + "
                         "flight-recorder trajectory), write "
                         "BOOT_BENCH.json, and exit")
    ap.add_argument("--boot-versions", type=int, default=10_000,
                    help="history size for --boot")
    ap.add_argument("--write", action="store_true",
                    help="run the per-tx vs group-commit WRITE "
                         "microbenchmark (1k/10k transactions, 1/8/32 "
                         "concurrent writers, p99 latency, event-loop "
                         "stall, converged-state parity), write "
                         "WRITE_BENCH.json, and exit")
    ap.add_argument("--write-txns", type=int, default=10_000,
                    help="largest transaction count for --write")
    ap.add_argument("--subs", action="store_true",
                    help="run the subscription fan-out benchmark "
                         "(sharded columnar matcher vs per-sub oracle "
                         "at the 100k-sub/10k-change headline with "
                         "in-bench verdict parity, mixed read/write/"
                         "subscribe swarm under staleness + stall "
                         "gates, paired subs-off/on write-path A/B), "
                         "write SUBS_BENCH.json, and exit")
    ap.add_argument("--subs-n", type=int, default=100_000,
                    help="standing subscription count for --subs")
    ap.add_argument("--subs-changes", type=int, default=10_000,
                    help="change-burst size for --subs")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    if args.check:
        args.nodes, args.seeds, args.config = 4096, 8, "5"

    if args.apply:
        # pure-sqlite benchmark: no JAX/compile-cache setup needed
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "APPLY_BENCH.json"
        )
        _emit(run_apply_bench(out_path=out_path))
        return
    if args.sync:
        # pure-sqlite + loopback benchmark: no JAX setup needed
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "SYNC_BENCH.json"
        )
        _emit(run_sync_bench(n_versions=args.sync_versions,
                             out_path=out_path))
        return
    if args.boot:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BOOT_BENCH.json"
        )
        _emit(run_boot_bench(n_versions=args.boot_versions,
                             out_path=out_path))
        return
    if args.write:
        # pure-sqlite + loopback benchmark: no JAX setup needed
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "WRITE_BENCH.json"
        )
        _emit(run_write_bench(
            sizes=tuple(sorted({min(1000, args.write_txns),
                                args.write_txns})),
            out_path=out_path))
        return
    if args.subs:
        # sqlite + numpy-backend kernel: no JAX setup needed
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "SUBS_BENCH.json"
        )
        _emit(run_subs_bench(n_subs=args.subs_n,
                             n_changes=args.subs_changes,
                             out_path=out_path))
        return
    if args.capture_topology:
        # virtual-time cluster campaign: no JAX setup needed
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "TOPOLOGY_MEASURED.json",
        )
        _emit(run_capture_topology(out_path=out_path))
        return
    if args.frontier:
        # the 10M multi-host headline and the multi-host gate need a
        # >= 2-device mesh to emulate hosts: self-provision the same
        # 8-device virtual CPU mesh tests/conftest.py uses when the
        # backend is CPU and not yet initialized (a real multi-chip
        # backend — JAX_PLATFORMS=tpu — is left alone)
        from __graft_entry__ import _backend_initialized, _force_virtual_cpu

        plat = os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0]
        if plat == "cpu" and not _backend_initialized():
            _force_virtual_cpu(8)
    _enable_compile_cache()
    if args.frontier:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_FRONTIER.json",
        )
        topo_names = (
            tuple(t.strip() for t in args.topology.split(",") if t.strip())
            if args.topology else None
        )
        _emit(run_frontier_bench(out_path=out_path,
                                 topo_names=topo_names,
                                 topology_json=args.topology_json))
        return
    if args.calibrate_msgs:
        from corrosion_tpu.sim.calibrate import run_msgs_calibration

        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "CALIB_MSGS.json"
        )
        _emit(run_msgs_calibration(out_path=out_path))
        return
    if args.timeline:
        n = args.n or (
            512 if args.virtual_time else args.timeline_nodes
        )
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"TIMELINE_N{n}.json",
        )
        if args.virtual_time:
            from corrosion_tpu.sim.timeline import run_virtual_timeline

            _emit(run_virtual_timeline(n=n, out_path=out_path))
            return
        _emit(run_timeline_bench(
            n=n, out_path=out_path,
        ))
        return
    if args.obs:
        from corrosion_tpu.sim.obs import run_obs

        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"OBS_N{args.obs_nodes}.json",
        )
        _emit(asyncio.run(run_obs(
            n=args.obs_nodes, writes=args.obs_writes, out_path=out_path,
        )))
        return
    if args.chaos:
        from corrosion_tpu.sim.chaos import run_chaos

        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"CHAOS_N{args.chaos_nodes}.json",
        )
        _emit(asyncio.run(
            run_chaos(n=args.chaos_nodes, out_path=out_path)
        ))
        return
    if args.scenarios:
        n = args.n or (
            512 if args.virtual_time else args.scenario_nodes
        )
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"SCENARIOS_N{n}.json",
        )
        families = (
            [f.strip() for f in args.scenario_families.split(",")
             if f.strip()]
            if args.scenario_families else None
        )
        if args.virtual_time:
            from corrosion_tpu.sim.scenarios import run_virtual_scenarios

            _emit(run_virtual_scenarios(
                n=n, families=families, out_path=out_path,
            ))
            return
        from corrosion_tpu.sim.scenarios import run_scenarios

        _emit(asyncio.run(run_scenarios(
            n=n, families=families,
            out_path=out_path,
        )))
        return
    from corrosion_tpu.sim import EpidemicConfig

    want = (set("12345") if args.config == "all"
            else set(args.config.replace(",", "")))
    if not want or not want <= set("12345"):
        ap.error(f"--config must be digits 1-5 or 'all', got {args.config!r}")
    results: dict = {}

    def _attempt(key: str, fn) -> None:
        # a failed config must not abort the sweep (config #1 runs real
        # agents on loopback and is subject to wall-clock flakiness)
        try:
            results[key] = fn()
        except Exception as e:  # noqa: BLE001 - surfaced in the output
            results[key] = {"metric": key, "value": None,
                            "error": f"{type(e).__name__}: {e}"}
        _emit(results[key])

    if "1" in want:
        _attempt("devcluster3", lambda: asyncio.run(_devcluster3()))
        # the exactness half of the north star ("bit-match
        # corro-devcluster at N<=256"): real agents under the
        # discrete-event scheduler vs the sim's deterministic replay,
        # per-tick infected sets + per-node msg counts exactly equal
        _attempt("bitmatch", _bitmatch)
    if "2" in want:
        _attempt("swim_churn_64", _churn64)
    if "3" in want:
        cfg3 = EpidemicConfig(
            n_nodes=1000, n_rows=args.rows,
            fanout_ring0=2, fanout_global=2, ring0_size=256,
            max_transmissions=8, loss=0.0,
            sync_interval=0,  # gossip only: fanout + LWW merge
            max_ticks=64, chunk_ticks=8,
        )
        _attempt("fanout_lww_1k", lambda: _epidemic(
            "broadcast_fanout_lww_1k_wall", cfg3, args.seeds))
    if "4" in want:
        _attempt("anti_entropy_10k", lambda: _anti_entropy(args.seeds))

    def _headline_cfg(n: int) -> "EpidemicConfig":
        return EpidemicConfig(
            n_nodes=n, n_rows=args.rows,
            fanout_ring0=2, fanout_global=2, ring0_size=256,
            max_transmissions=8, loss=0.05,
            partition_blocks=2, heal_tick=12,
            sync_interval=8, sync_peers=1,
            max_ticks=192, chunk_ticks=16,
        )

    def _exact_cfg(n: int, partitioned: bool) -> "HeadlineExactConfig":
        return _frontier_exact_cfg(n, partitioned)

    def _exact_seed_policy(n: int) -> int:
        """Real rank statistics per sweep N: 32 seeds through 64k,
        16 at 100k, 4 at the 256k/1M stretch points — all seed-parallel
        (vmapped batches; the governing state sets the batch)."""
        if n <= 64_000:
            return min(args.seeds, 32)
        if n <= 100_000:
            return min(args.seeds, 16)
        return min(args.seeds, 4)

    def _run_exact(n: int, partitioned: bool) -> dict:
        return _run_exact_planned(
            _exact_cfg(n, partitioned), _exact_seed_policy(n)
        )

    # the metric is "p99 convergence + msgs/node VS CLUSTER SIZE N":
    # beyond the per-config series (heterogeneous protocols), sweep the
    # HEADLINE protocol itself over N with identical parameters (the
    # N == args.nodes point is filled from the headline run below).
    # Each row carries BOTH delivery models: the fast perm-fanout
    # kernel (hops + the 60s-budget wall) and the bitpacked EXACT
    # sampler measured at the same n — no extrapolated estimates.
    if want == set("12345") and not args.check:
        def _sweep() -> dict:
            from corrosion_tpu.sim import run_epidemic_seeds

            points = []
            for n in (1000, 4000, 16000, 64000, 100000):
                ex = _run_exact(n, partitioned=True)
                if n == args.nodes:
                    # perm stats spliced in from the headline run below
                    # (avoids re-running the priciest N); until then the
                    # row carries the exact block + a note, so the
                    # streamed record is well-formed even if the
                    # headline run later fails
                    points.append({
                        "n": n,
                        "note": (
                            "perm-fanout stats for this n come from "
                            "the headline run (spliced in the final "
                            "record)"
                        ),
                        "exact": _exact_block(ex),
                    })
                    continue
                cfg_n = _headline_cfg(n)
                run_epidemic_seeds(cfg_n, n_seeds=args.seeds, seed=1)
                # warm run above pays compile; the measured wall doesn't
                s = run_epidemic_seeds(cfg_n, n_seeds=args.seeds, seed=0)
                points.append(_sweep_point(n, s, exact=ex))
            value = next(
                (p["ticks_p99"] for p in reversed(points)
                 if "ticks_p99" in p),
                None,
            )
            return {
                "metric": "epidemic_sweep_p99_and_msgs_vs_n",
                "value": value,
                "unit": "ticks",
                "msgs_note": (
                    "each row carries two measured models of the "
                    "headline protocol family: perm-fanout (the "
                    "TPU-fast collision-free kernel with a per-tick "
                    "ring0 tier; supplies hop depths and the 60s-"
                    "budget wall) and the exact sampler (the det/"
                    "bitmatch-validated AGENT protocol: uniform "
                    "sent_to-excluding draws, ring0 tier on the "
                    "origin's first flush only; sim/calibrate.py "
                    "run_exact_headline, [N, N/8] bitpacked sent_to) "
                    "run AT that n — the exact column is the agents' "
                    "measured msgs/node, not a ratio estimate; the "
                    "two columns model ring0 differently and are not "
                    "two samplers of one process"
                ),
                "points": points,
            }

        _attempt("epidemic_sweep_vs_n", _sweep)

        # the same protocol WITHOUT the partition (loss only): the
        # ticks-vs-N column now measures epidemic depth (~log N)
        # instead of the heal-tick + sync-boundary schedule that pins
        # the partitioned series at one value (round-4 weak #3); the
        # partitioned series above stays as the stress case
        def _sweep_lossonly() -> dict:
            points = []
            # beyond 100k the representation changes with N (kernel
            # dispatch per the device-memory bitmap budget): 256k
            # row-shards the dense bitmap where the mesh allows, 1M
            # runs the frontier-sparse kernel (the [N, N/8] bitmap is
            # ~125 GB there — no backend places it).  A failure at any
            # stretch point must not void the rest of the series, so
            # each point is individually guarded
            for n in (1000, 4000, 16000, 64000, 100000, 256000,
                      1000000):
                try:
                    ex = _run_exact(n, partitioned=False)
                except Exception as e:  # noqa: BLE001 - surfaced below
                    points.append({
                        "n": n,
                        "error": f"{type(e).__name__}: {e}",
                        "note": (
                            "exact point unavailable on this backend; "
                            "see the memory budget tables in "
                            "docs/sim.md"
                        ),
                    })
                    continue
                points.append({
                    "n": n,
                    "ticks_p50": ex["ticks_p50"],
                    "ticks_p99": ex["ticks_p99"],
                    "msgs_per_node_mean": round(
                        ex["msgs_per_node_mean"], 2),
                    "msgs_per_node_p99": round(ex["msgs_per_node_p99"], 2),
                    "converged_frac": ex["converged_frac"],
                    "delivery_model": "exact-rejection-sampler",
                    "kernel": ex.get("kernel"),
                    "n_seeds": ex["n_seeds"],
                    "seed_batch": ex.get("seed_batch"),
                    "n_shards": ex.get("n_shards"),
                    "wall_s": round(ex["wall_s"], 2),
                })
            last_ok = next(
                (p for p in reversed(points) if "ticks_p99" in p), None
            )
            return {
                "metric": "epidemic_lossonly_ticks_vs_n",
                "value": last_ok["ticks_p99"] if last_ok else None,
                "unit": "ticks",
                "conditions": (
                    "headline protocol, 5% loss, NO partition — "
                    "convergence depth scales with N instead of being "
                    "pinned to the heal schedule; each point records "
                    "the kernel (dense / sharded-dense / sparse) the "
                    "bitmap-budget dispatch selected, and the 1M point "
                    "is the frontier-sparse kernel's headline"
                ),
                "points": points,
            }

        _attempt("epidemic_lossonly_vs_n", _sweep_lossonly)

    headline = None
    if "5" in want:
        cfg5 = _headline_cfg(args.nodes)
        try:
            headline = _epidemic(
                f"epidemic_convergence_sim_{args.nodes//1000}k_nodes_wall",
                cfg5, args.seeds, headline=True)
        except Exception as e:  # noqa: BLE001 - surfaced in the output
            _emit({"metric": "epidemic_convergence_sim",
                   "value": None, "error": f"{type(e).__name__}: {e}"})
            return
        results["epidemic_100k"] = headline
        if headline["converged_frac"] < 1.0:
            print(json.dumps(_sanitize(
                {"error": "did not converge", **headline})),
                  file=sys.stderr)

    if headline is not None:
        sweep = results.get("epidemic_sweep_vs_n")
        if sweep and "points" in sweep:
            # splice the headline's own point into the sweep (same
            # config constructor; avoids re-running the priciest N) —
            # its exact block was parked by the sweep loop
            parked = next(
                (p for p in sweep["points"]
                 if "exact" in p and "ticks_p50" not in p),
                None,
            )
            spliced = _sweep_point(headline["n_nodes"], {
                **headline,
                "msgs_per_node_mean": headline.get(
                    "msgs_per_node_mean", 0.0),
                "converged_frac": headline.get("converged_frac"),
                "wall_s": headline.get("value"),
            }, exact=parked["exact"] if parked else None)
            if parked is not None:
                sweep["points"].remove(parked)
            sweep["points"].append(spliced)
            sweep["points"].sort(key=lambda p: p["n"])
            sweep["value"] = sweep["points"][-1]["ticks_p99"]
        baseline_s = 60.0  # BASELINE.json north-star budget on v5e-8
        series = sorted(
            (r["n_nodes"], r["msgs_per_node_mean"], k)
            for k, r in results.items()
            if "msgs_per_node_mean" in r and "error" not in r
        )
        final = dict(headline)
        final["vs_baseline"] = round(
            baseline_s / max(final["value"], 1e-9), 2)
        if len(results) > 1:
            final["configs"] = {
                k: v for k, v in results.items() if k != "epidemic_100k"
            }
            # note: swim_churn_64 counts MEMBERSHIP traffic (probes/acks
            # over the whole churn cycle); the others count change
            # dissemination — keep the config key so the units read
            final["msgs_per_node_vs_n"] = [
                {"n": n, "msgs_per_node": m, "config": k}
                for n, m, k in series
            ]
        _emit(final)
    if args.verbose:
        print(json.dumps(_sanitize(results), indent=2), file=sys.stderr)


if __name__ == "__main__":
    main()
